"""Online health plane (ISSUE 14): streaming sketches, SLO
accounting, flight recorder.

The contracts under test:

* PARITY — the health sink OBSERVES, it never perturbs: on/off runs
  are bit-identical across the chaos matrix (the PR 8 bar), and off
  mode is one `is None` check per trace record.
* SKETCHES — log-bucketed merges are associative/commutative, memory
  stays bounded past the site cap, and quantile estimates land in the
  right bucket.
* CROSS-PROCESS — a worker's injected-delay fetch tail surfaces in
  the DRIVER's merged per-site view (the process.counters digest
  ride-along), and lands in the adapt store keyed by site — the
  ROADMAP item 5 handoff, proven end to end.
* SLO — a 2-tenant JobServer cell counts one tenant's violations and
  exports them on /metrics while the other tenant stays at 100%
  attainment, and /api/health grades the subsystem with evidence.
* FLIGHT — warning events land in the always-armed ring even with
  DPARK_TRACE=off; job abort and SIGUSR2 dump crc-framed snapshots
  that tools/dtrace --flight reads back.
"""

import json
import os
import signal
import threading
import time
import urllib.request

import pytest

from dpark_tpu import conf, faults, health, trace


@pytest.fixture(autouse=True)
def _clean_planes(tmp_path):
    """Every test starts and ends with a fresh sink, no trace/chaos
    planes, no process-global service, and flight state reset."""
    from dpark_tpu import service
    trace.configure("off")
    faults.configure(None)
    health.configure("on")
    trace._FLIGHT.clear()
    health._flight_dumps = 0
    old_flight = conf.DPARK_FLIGHT_DIR
    conf.DPARK_FLIGHT_DIR = ""
    yield
    service.shutdown()
    trace.configure("off")
    faults.configure(None)
    health.configure("on")
    trace._FLIGHT.clear()
    health._flight_dumps = 0
    conf.DPARK_FLIGHT_DIR = old_flight


def _reduce_job(c, n=500, parts=4, reduce_parts=3):
    return dict(c.parallelize([(i % 5, 1) for i in range(n)], parts)
                .reduceByKey(lambda a, b: a + b,
                             reduce_parts).collect())


# ---------------------------------------------------------------------------
# sketches
# ---------------------------------------------------------------------------

def test_sketch_buckets_and_quantiles():
    sk = health.Sketch()
    for _ in range(97):
        sk.add(0.001)               # 1 ms
    for _ in range(3):
        sk.add(1.0)                 # 1 s stragglers (3% tail)
    assert sk.n == 100
    p50 = sk.quantile(0.50)
    p99 = sk.quantile(0.99)
    # p50 sits in the ~1 ms bucket; p99 reaches the stragglers' bucket
    assert 0.0004 < p50 < 0.004, p50
    assert p99 > 0.25, p99
    s = sk.summary()
    assert s["n"] == 100 and s["p99_ms"] > 250


def test_sketch_merge_associative_and_commutative():
    import random
    rng = random.Random(7)
    parts = []
    for _ in range(4):
        sk = health.Sketch()
        for _ in range(200):
            sk.add(rng.random() ** 4)
        parts.append(sk)

    def fold(order):
        acc = health.Sketch()
        for i in order:
            acc.merge(health.Sketch.from_dict(parts[i].to_dict()))
        return acc.to_dict()

    a = fold([0, 1, 2, 3])
    b = fold([3, 1, 0, 2])
    # ((0+1)+(2+3)) via digest round-trips
    left = health.merge_digests(
        health.merge_digests(parts[0].to_dict(), parts[1].to_dict()),
        health.merge_digests(parts[2].to_dict(), parts[3].to_dict()))
    assert a == b == left
    assert health.Sketch.from_dict(a).n == 800


def test_sketch_digest_roundtrip_ignores_garbage():
    sk = health.Sketch.from_dict({"b": {"3": 5, "999": 7, "x": 1},
                                  "n": "not-an-int"})
    assert sk.buckets[3] == 5
    assert sum(sk.buckets) == 5          # out-of-range/garbage skipped


def test_sink_bounded_past_site_cap(monkeypatch):
    monkeypatch.setattr(conf, "HEALTH_MAX_SITES", 8)
    s = health.HealthSink()
    for i in range(1000):
        s.fold({"name": "fetch.bucket", "dur": 0.001,
                "args": {"peer": "host-%d" % i}})
    # memory bounded: the cap plus a few base-site overflow slots
    assert len(s.sites) <= 8 + 16
    assert s.dropped_sites > 0
    # no observation was lost: total count across sites is exact
    assert sum(sk.n for sk in s.sites.values()) == 1000


def test_off_mode_is_one_predicate():
    health.configure("off")
    assert health._SINK is None
    assert health.mode() == "off"
    assert health.summary() == {"mode": "off", "sites": {},
                                "rates": {}}
    with pytest.raises(ValueError):
        health.configure("loud")


# ---------------------------------------------------------------------------
# parity: the sink observes, never perturbs (chaos matrix)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [
    None,
    "shuffle.fetch:p=0.3,seed=11,times=3",
    "shuffle.spill_write:nth=1,kind=corrupt",
])
def test_health_on_off_parity_chaos_matrix(ctx, tmp_path, spec):
    pairs = [(i % 11, i) for i in range(500)]

    def run():
        faults.configure(spec)
        try:
            return dict(ctx.parallelize(pairs, 4)
                        .groupByKey(3)
                        .mapValues(sorted).collect())
        finally:
            faults.configure(None)

    health.configure("off")
    expected = run()                     # health off, trace off
    for mode in ("ring", "spool"):
        trace.configure(mode, str(tmp_path / mode))
        health.configure("on")
        try:
            assert run() == expected, (mode, spec)
            assert health.snapshot()["folded"] > 0
            assert any(k.startswith("fetch.bucket")
                       for k in health.snapshot()["sites"])
        finally:
            trace.configure("off")
        # off side under the same trace mode: zero folds
        trace.configure(mode, str(tmp_path / (mode + "-off")))
        health.configure("off")
        try:
            assert run() == expected, (mode, spec)
        finally:
            trace.configure("off")
        health.configure("on")


@pytest.fixture()
def tiny_waves():
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 500
    yield
    conf.STREAM_CHUNK_ROWS = old


@pytest.mark.parametrize("spec", [
    None,
    "shuffle.fetch:p=0.3,seed=11,times=3",
])
def test_health_parity_device(tctx2, tiny_waves, tmp_path, spec):
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(4000, dtype=np.int64)
    data = Columns(i % 37, i & 0xFF)

    def run():
        faults.configure(spec)
        try:
            return dict(tctx2.parallelize(data, 2)
                        .reduceByKey(lambda a, b: a + b, 2).collect())
        finally:
            faults.configure(None)

    health.configure("off")
    expected = run()
    trace.configure("spool", str(tmp_path / "dev"))
    health.configure("on")
    try:
        assert run() == expected
        sites = health.snapshot()["sites"]
        # device execution landed in the sketches, keyed by program
        # signature
        assert any(k.startswith("wave:") for k in sites), sites
        assert "stage.exec" in sites, sites
    finally:
        trace.configure("off")


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


# ---------------------------------------------------------------------------
# cross-process tail merge (the multiproc half of the item-5 handoff)
# ---------------------------------------------------------------------------

@pytest.fixture()
def fresh_forkserver():
    from multiprocessing import forkserver

    def stop():
        try:
            forkserver._forkserver._stop()
        except Exception:
            pass

    stop()
    yield
    stop()


def test_worker_fetch_tail_surfaces_on_driver(fresh_forkserver, pctx,
                                              tmp_path, monkeypatch):
    """Workers run the reduces (and therefore the fetches) in their
    own processes; an injected 120 ms fetch delay there must surface
    in the DRIVER's merged per-site tail view via the counters-file
    digest ride-along — and persist into the adapt store keyed by
    site."""
    from dpark_tpu import adapt
    monkeypatch.setenv("DPARK_FAULTS",
                       "shuffle.fetch:nth=1,kind=delay,ms=120")
    store = str(tmp_path / "adapt")
    adapt.configure(mode="observe", store_dir=store)
    trace.configure("spool", str(tmp_path / "mp"))
    try:
        assert _reduce_job(pctx, n=400) == {k: 80 for k in range(5)}
        # the driver process itself fetched nothing...
        own = health.snapshot()["sites"]
        assert not any(k.startswith("fetch.bucket") for k in own), own
        # ...but the merged view carries the workers' sketches
        merged = health.merged_site_digests()
        fetch_sites = {k: v for k, v in merged.items()
                       if k.startswith("fetch.bucket")}
        assert fetch_sites, merged
        summaries = health.summarize_sites(fetch_sites)
        worst = max(s.get("p99_ms", 0.0) for s in summaries.values())
        assert worst >= 50.0, summaries    # the 120 ms delay is in the tail
        # adapt-store handoff: the job-finish hook already persisted
        # the merged deltas (a second forced persist finds nothing
        # new — deltas never double-count); read back as a fresh
        # process would (configure() resets all in-memory state)
        assert health.persist_site_tails(force=True) == 0
        adapt.configure(mode="observe", store_dir=store)
        assert any(k.startswith("fetch.bucket")
                   for k in adapt.summary()["sites"])
        tails = adapt.site_tails()
        site = next(k for k in tails if k.startswith("fetch.bucket"))
        sk = health.Sketch.from_dict(tails[site])
        assert sk.n >= 1
        # stored tails read back as REAL latency sketches: the sum
        # delta persisted too, so summary() reports percentiles (a
        # zeroed sum would misclassify them as count-only)
        assert "p99_ms" in sk.summary(), sk.to_dict()
        # the stored distribution still shows the delayed fetch:
        # some mass sits at or above the ~100 ms buckets
        slow = sum(sk.buckets[health.Sketch.bucket_of(0.1):])
        assert slow >= 1, tails[site]
    finally:
        trace.configure("off")
        adapt.configure()


# ---------------------------------------------------------------------------
# per-tenant SLO accounting (2-tenant JobServer cell)
# ---------------------------------------------------------------------------

def test_two_tenant_slo_violations_and_attainment(tmp_path):
    from dpark_tpu import DparkContext, service
    from dpark_tpu.service import ClientScheduler
    from dpark_tpu.web import render_metrics
    ctx = DparkContext("service:local")
    ctx.start()
    try:
        srv = ctx.scheduler.server
        # tenant-slow declares an impossible target (every job
        # violates); tenant-fast a generous one (every job attains)
        slow = ClientScheduler(srv, client="tenant-slow",
                               slo_ms=0.001)
        fast = ClientScheduler(srv, client="tenant-fast",
                               slo_ms=60000)
        rdd = ctx.parallelize([(i % 5, 1) for i in range(200)], 4) \
            .reduceByKey(lambda a, b: a + b, 3)
        for sched in (slow, fast, slow, fast, slow):
            got = dict(x for part in sched.run_job(
                rdd, lambda it: list(it)) for x in part)
            assert got == {k: 40 for k in range(5)}
        stats = srv.tenant_slo_stats()
        ts, tf = stats["tenant-slow"], stats["tenant-fast"]
        assert ts["jobs"] == 3 and ts["violations_total"] == 3, ts
        assert ts["attainment"] == 0.0
        assert tf["jobs"] == 2 and tf["violations_total"] == 0, tf
        assert tf["attainment"] == 1.0
        # burn: violations consume the error budget far faster than
        # allowed for the slow tenant, not at all for the fast one
        assert max(ts["burn"].values()) > 2.0, ts
        assert max(tf["burn"].values()) == 0.0, tf
        # the per-job verdict rides the record (web UI SLO column)
        recs = [r for r in srv.scheduler.history
                if r.get("client") == "tenant-slow"]
        assert all(r.get("slo", {}).get("ok") is False for r in recs)
        # /metrics export
        body = render_metrics(ctx.scheduler)
        assert ('dpark_tenant_slo_violations_total'
                '{tenant="tenant-slow"} 3') in body, body
        assert ('dpark_tenant_slo_violations_total'
                '{tenant="tenant-fast"} 0') in body
        assert 'dpark_tenant_slo_attainment{tenant="tenant-fast"} 1.0' \
            in body
        # /api/health grades the subsystem red with evidence attached
        api = health.api_health(ctx.scheduler)
        slo_sub = api["subsystems"]["service_slo"]
        assert slo_sub["grade"] == "red", slo_sub
        ev = slo_sub["evidence"]
        assert ev["tenants"]["tenant-slow"]["violations_total"] == 3
        assert "thresholds" in ev
    finally:
        ctx.stop()
        from dpark_tpu import service as service_mod
        service_mod.shutdown()


def test_service_slo_env_default(monkeypatch):
    """DPARK_SERVICE_SLO (conf.SERVICE_SLO_MS) applies to tenants
    that declare nothing."""
    from dpark_tpu import DparkContext
    monkeypatch.setattr(conf, "SERVICE_SLO_MS", 45000.0)
    ctx = DparkContext("service:local")
    ctx.start()
    try:
        assert _reduce_job(ctx, 200) == {k: 40 for k in range(5)}
        stats = ctx.scheduler.server.tenant_slo_stats()
        (tenant,) = stats
        assert stats[tenant]["slo_ms"] == 45000.0
        assert stats[tenant]["attainment"] == 1.0
    finally:
        ctx.stop()
        from dpark_tpu import service as service_mod
        service_mod.shutdown()


# ---------------------------------------------------------------------------
# /api/health endpoint + web UI columns
# ---------------------------------------------------------------------------

def test_api_health_endpoint_and_stage_p99(ctx):
    from dpark_tpu.web import start_ui
    trace.configure("ring")
    _reduce_job(ctx)
    server, url = start_ui(ctx.scheduler)
    try:
        with urllib.request.urlopen(url + "api/health") as r:
            assert r.status == 200
            api = json.loads(r.read().decode())
        assert api["mode"] == "on"
        assert any(k.startswith("fetch.bucket") for k in api["sites"])
        for sub in ("shuffle_fetch", "dcn", "coding", "executor",
                    "spill", "scheduler"):
            assert api["subsystems"][sub]["grade"] in (
                "green", "yellow", "red"), sub
            assert "evidence" in api["subsystems"][sub]
        # the stage fetch sketches feed the web UI's fetch-p99 column
        assert api["stage_fetch"], api
        assert all("n" in v for v in api["stage_fetch"].values())
    finally:
        server.shutdown()
        trace.configure("off")


def test_page_has_health_columns():
    from dpark_tpu import web
    assert "fetch p99 ms" in web._PAGE
    assert "SLO (attain %)" in web._PAGE
    assert "/api/health" in web._PAGE


def test_api_health_never_throws_mid_mutation(ctx):
    """Same discipline as /metrics: a poisoned history record must
    not break the endpoint."""
    trace.configure("ring")
    _reduce_job(ctx)
    ctx.scheduler.history.append(
        {"id": 99, "state": None, "stage_info": ["not-a-dict"]})
    try:
        api = health.api_health(ctx.scheduler)
    finally:
        ctx.scheduler.history.pop()
        trace.configure("off")
    assert json.dumps(api)


# ---------------------------------------------------------------------------
# offline twin: dtrace --health vs the live endpoint
# ---------------------------------------------------------------------------

def _load_dtrace():
    from tests.conftest import load_tool
    return load_tool("dtrace")


def test_dtrace_health_matches_live_endpoint(ctx, tmp_path, capsys):
    d = str(tmp_path / "spool")
    trace.configure("spool", d)
    health.configure("on")            # fresh sink scoped to this run
    _reduce_job(ctx)
    live_digests = health.merged_site_digests()
    live_rates = dict(health.snapshot()["rates"])
    trace.configure("off")
    dtrace = _load_dtrace()
    assert dtrace.main(["--health", "--dir", d]) == 0
    offline = json.loads(capsys.readouterr().out)
    # the offline twin folded the SAME records the live sink saw, so
    # site summaries and sketch-fed grades agree exactly
    assert offline["sites"] == health.summarize_sites(live_digests)
    live_grades = health.grade(live_digests, live_rates)
    for sub in ("shuffle_fetch", "dcn", "coding", "executor", "spill"):
        assert offline["subsystems"][sub]["grade"] \
            == live_grades[sub]["grade"], sub
    # empty spool fails (the CI gate contract)
    assert dtrace.main(["--health", "--dir",
                        str(tmp_path / "empty")]) == 1


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_always_armed_in_off_mode():
    assert trace.mode() == "off"
    trace.flight("fetch.failed", "shuffle", shuffle=3, error="IOError")
    ring = trace.flight_snapshot()
    assert ring and ring[-1]["name"] == "fetch.failed"
    assert ring[-1]["sev"] == "warn"
    # and the sink folded the failure rate even without a trace plane
    assert health.snapshot()["rates"].get("fetch.failed") == 1


def test_error_spans_mirror_into_flight_ring(tmp_path):
    trace.configure("ring")
    with pytest.raises(RuntimeError):
        with trace.span("work", "test"):
            raise RuntimeError("no")
    trace.configure("off")
    assert any(r["name"] == "work" for r in trace.flight_snapshot())


def test_flight_event_lands_once_with_plane_installed(tmp_path):
    """An error-carrying flight event must occupy ONE ring slot even
    though plane.record also mirrors error records — a failure storm
    must not halve the ring's effective capacity."""
    trace.configure("ring")
    trace.flight("fetch.failed", "shuffle", shuffle=1, error="IOError")
    trace.configure("off")
    hits = [r for r in trace.flight_snapshot()
            if r["name"] == "fetch.failed"]
    assert len(hits) == 1, hits
    assert hits[0]["sev"] == "warn"


def test_worker_health_file_is_o1_per_process(ctx, tmp_path):
    """The per-process health digest file is rewritten latest-wins —
    many jobs/tasks leave exactly one record, not one per task (the
    counters file is append-only and uncapped, so digests must not
    ride it)."""
    d = str(tmp_path / "o1")
    trace.configure("spool", d)
    health.configure("on")
    for _ in range(3):
        _reduce_job(ctx)
        trace.emit_process_counters()
    trace.configure("off")
    hf = [f for f in os.listdir(d) if f.startswith("health-")]
    assert len(hf) == 1, hf
    recs, skipped = __import__(
        "dpark_tpu.utils", fromlist=["unframe_jsonl"]).unframe_jsonl(
        open(os.path.join(d, hf[0]), "rb").read())
    assert skipped == 0 and len(recs) == 1, (len(recs), skipped)
    assert recs[0]["name"] == "process.health"
    assert any(k.startswith("fetch.bucket")
               for k in recs[0]["args"]["health"])


def test_flight_dump_disabled_without_dir(ctx):
    assert conf.DPARK_FLIGHT_DIR == ""
    assert health.flight_dump("manual", scheduler=ctx.scheduler) \
        is None


def test_flight_dump_on_job_abort_and_dtrace_roundtrip(ctx, tmp_path,
                                                       capsys):
    conf.DPARK_FLIGHT_DIR = str(tmp_path / "flight")

    def boom(x):
        raise ValueError("injected abort")

    with pytest.raises(RuntimeError):
        ctx.parallelize([1, 2], 2).map(boom).collect()
    dumps = os.listdir(conf.DPARK_FLIGHT_DIR)
    assert dumps, "abort produced no flight dump"
    path = os.path.join(conf.DPARK_FLIGHT_DIR, sorted(dumps)[0])
    recs = health.load_flight(path)
    kinds = {r["kind"] for r in recs}
    assert {"flight.header", "flight.event", "flight.health",
            "flight.job", "flight.recovery", "flight.adapt"} <= kinds
    header = next(r for r in recs if r["kind"] == "flight.header")
    assert header["reason"].startswith("job-abort")
    # the ring carried the abort event
    names = {(r.get("rec") or {}).get("name") for r in recs
             if r["kind"] == "flight.event"}
    assert "job.abort" in names, names
    job = next(r for r in recs if r["kind"] == "flight.job")
    assert job["record"]["state"] == "aborted"
    # dtrace --flight round-trip
    dtrace = _load_dtrace()
    assert dtrace.main(["--flight", path]) == 0
    out = capsys.readouterr().out
    assert "job-abort" in out and "warning-and-above" in out
    # an unusable dump fails
    bad = str(tmp_path / "bad.jsonl")
    open(bad, "w").write("garbage\n")
    assert dtrace.main(["--flight", bad]) == 1


def test_flight_dump_on_stage_degrade(ctx, tmp_path):
    conf.DPARK_FLIGHT_DIR = str(tmp_path / "flight")
    _reduce_job(ctx)                 # starts the lazy scheduler
    sched = ctx.scheduler
    sched._current_record = {"id": 1, "stage_info": []}
    try:
        sched.note_stage(7, degrade_reason="test degrade")
    finally:
        sched._current_record = None
    assert any(f.startswith("flight-")
               for f in os.listdir(conf.DPARK_FLIGHT_DIR))
    assert any(r["name"] == "stage.degrade"
               for r in trace.flight_snapshot())


def test_flight_dump_on_sigusr2(ctx, tmp_path):
    conf.DPARK_FLIGHT_DIR = str(tmp_path / "flight")
    _reduce_job(ctx)                 # job finish arms the handler
    assert health._sigusr2_installed or health.install_sigusr2()
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = time.time() + 5
    found = []
    while time.time() < deadline and not found:
        if os.path.isdir(conf.DPARK_FLIGHT_DIR):
            found = [f for f in os.listdir(conf.DPARK_FLIGHT_DIR)]
        time.sleep(0.01)
    assert found, "SIGUSR2 produced no flight dump"
    recs = health.load_flight(
        os.path.join(conf.DPARK_FLIGHT_DIR, found[0]))
    header = next(r for r in recs if r["kind"] == "flight.header")
    assert header["reason"] == "sigusr2"


def test_flight_dump_cap(ctx, tmp_path, monkeypatch):
    conf.DPARK_FLIGHT_DIR = str(tmp_path / "flight")
    monkeypatch.setattr(conf, "FLIGHT_MAX_DUMPS", 2)
    assert health.flight_dump("one") is not None
    assert health.flight_dump("two") is not None
    assert health.flight_dump("three") is None      # capped
    assert len(os.listdir(conf.DPARK_FLIGHT_DIR)) == 2


# ---------------------------------------------------------------------------
# bench schema ride-alongs
# ---------------------------------------------------------------------------

def test_health_summary_schema(ctx):
    trace.configure("ring")
    _reduce_job(ctx)
    s = health.summary()
    trace.configure("off")
    assert s["mode"] == "on"
    assert isinstance(s["sites"], dict) and s["sites"]
    site = next(k for k in s["sites"] if k.startswith("fetch.bucket"))
    for field in ("n", "p50_ms", "p95_ms", "p99_ms"):
        assert field in s["sites"][site]
    assert json.dumps(s)
