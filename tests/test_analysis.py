"""Pre-flight plan linter + AST closure analyzer (dpark_tpu/analysis/).

Plan rules run over live lineage graphs; closure rules run both over
live callables (pre-flight) and over source files (the dlint CLI).
The local master is sufficient for every plan-shape assertion — the
rules are graph-structural and never execute device code."""

import json
import os
import subprocess
import sys

import pytest

from dpark_tpu.analysis import (PlanLintError, lint_function, lint_plan,
                                lint_source, preflight)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BAD_EXAMPLE = os.path.join(REPO, "tests", "fixtures",
                           "bad_lint_example.py")


def rules(report):
    return {f.rule for f in report}


# ---------------------------------------------------------------------------
# plan rules
# ---------------------------------------------------------------------------

def test_monoid_multileaf_fires_on_tuple_values(ctx):
    r = ctx.parallelize([(1, (2, 3)), (1, (5, 1)), (2, (7, 8))], 2) \
           .reduceByKey(lambda a, b: max(a, b))
    rep = lint_plan(r)
    assert "monoid-multileaf" in rules(rep)
    [f] = [f for f in rep if f.rule == "monoid-multileaf"]
    assert f.severity == "error"


def test_host_fallback_key_quiet_on_device_keys(ctx):
    """Scalar ints AND flat numeric tuple keys ride the array path now
    — the rule must stay quiet on both."""
    r = ctx.parallelize([(1, 2), (3, 4)], 2) \
           .reduceByKey(lambda a, b: a + b)
    assert "host-fallback-key" not in rules(lint_plan(r))
    r = ctx.parallelize([((1, 2), 3), ((4, 5), 6)], 2) \
           .reduceByKey(lambda a, b: a + b)
    assert "host-fallback-key" not in rules(lint_plan(r))


def test_host_fallback_key_fires_on_nested_tuple(ctx):
    r = ctx.parallelize([(((1, 2), 3), 4)], 2) \
           .reduceByKey(lambda a, b: a + b)
    rep = lint_plan(r)
    assert "host-fallback-key" in rules(rep)
    [f] = [f for f in rep if f.rule == "host-fallback-key"]
    assert f.severity == "warn"
    assert "nested" in f.message


def test_host_fallback_key_fires_on_non_numeric_leaf(ctx):
    r = ctx.parallelize([((1, "a"), 2)], 2) \
           .reduceByKey(lambda a, b: a + b)
    rep = lint_plan(r)
    [f] = [f for f in rep if f.rule == "host-fallback-key"]
    assert f.severity == "warn"
    assert "non-numeric key leaf" in f.message


def test_host_fallback_key_fires_on_too_wide_tuple(ctx):
    from dpark_tpu import conf
    wide = tuple(range(conf.MAX_KEY_LEAVES + 1))
    r = ctx.parallelize([(wide, 1)], 2).reduceByKey(lambda a, b: a + b)
    rep = lint_plan(r)
    [f] = [f for f in rep if f.rule == "host-fallback-key"]
    assert "MAX_KEY_LEAVES" in f.message


def test_host_fallback_key_float_hash_vs_range(ctx):
    """Float keys fall back on HASH shuffles (no device portable-hash
    twin) but ride range repartitioning — the rule mirrors both."""
    r = ctx.parallelize([(1.5, 1), (2.5, 2)], 2) \
           .reduceByKey(lambda a, b: a + b)
    rep = lint_plan(r)
    [f] = [f for f in rep if f.rule == "host-fallback-key"]
    assert "float key on a hash shuffle" in f.message
    s = ctx.parallelize([(1.5, 1), (2.5, 2)], 2).sortByKey()
    assert "host-fallback-key" not in rules(lint_plan(s))


def test_host_fallback_key_one_leaf_tuple(ctx):
    """A 1-leaf tuple is NOT a scalar key — layout.key_width rejects
    it, so the rule must report it (review finding: the first cut let
    it through silently)."""
    r = ctx.parallelize([((1,), 2), ((3,), 4)], 2) \
           .reduceByKey(lambda a, b: a + b)
    rep = lint_plan(r)
    [f] = [f for f in rep if f.rule == "host-fallback-key"]
    assert "1 leaves" in f.message


def test_host_fallback_key_string_is_info(ctx):
    """String keys are legitimate on the text-source path — the rule
    reports them at info severity, never warn."""
    r = ctx.parallelize([("w", 1), ("v", 2)], 2) \
           .reduceByKey(lambda a, b: a + b)
    rep = lint_plan(r)
    [f] = [f for f in rep if f.rule == "host-fallback-key"]
    assert f.severity == "info"


def test_monoid_multileaf_quiet_on_scalar_values(ctx):
    r = ctx.parallelize([(1, 2), (2, 3)], 2) \
           .reduceByKey(lambda a, b: max(a, b))
    assert "monoid-multileaf" not in rules(lint_plan(r))


def test_monoid_multileaf_quiet_on_unclassified_merge(ctx):
    # a per-field merge is the CORRECT spelling — must not be flagged
    r = ctx.parallelize([(1, (2, 3)), (2, (7, 8))], 2) \
           .reduceByKey(lambda a, b: (max(a[0], b[0]), max(a[1], b[1])))
    assert "monoid-multileaf" not in rules(lint_plan(r))


def test_error_mode_refuses_plan_before_launch(ctx, monkeypatch):
    monkeypatch.setenv("DPARK_LINT", "error")
    r = ctx.parallelize([(1, (2, 3)), (1, (5, 1)), (2, (7, 8))], 2) \
           .reduceByKey(lambda a, b: max(a, b))
    with pytest.raises(PlanLintError) as ei:
        r.collect()
    assert "monoid-multileaf" in str(ei.value)
    # warn mode lets the same plan run (the executor guard makes the
    # result correct via the raw-combiner exchange)
    monkeypatch.setenv("DPARK_LINT", "warn")
    assert sorted(r.collect()) == [(1, (5, 1)), (2, (7, 8))]


def test_join_repartition_rule(ctx):
    a = ctx.parallelize([(i, i) for i in range(10)], 2).partitionBy(3)
    b = ctx.parallelize([(i, -i) for i in range(10)], 2).partitionBy(3)
    assert "plan-join-repartition" in rules(lint_plan(a.join(b, 5)))
    # matching split counts keep the join narrow — no finding
    assert "plan-join-repartition" not in rules(lint_plan(a.join(b, 3)))


def test_uncached_reshuffle_rule(ctx):
    base = ctx.parallelize([(i % 3, i) for i in range(30)], 2) \
              .map(lambda kv: (kv[0], kv[1] + 1))
    fan = base.reduceByKey(lambda a, b: a + b, 2) \
              .union(base.groupByKey(2).mapValue(len))
    assert "plan-uncached-reshuffle" in rules(lint_plan(fan))
    base.cache()
    assert "plan-uncached-reshuffle" not in rules(lint_plan(fan))
    base.unpersist()


def test_wide_depth_rule(ctx, monkeypatch):
    from dpark_tpu import conf
    monkeypatch.setattr(conf, "LINT_WIDE_DEPTH", 2)
    r = ctx.parallelize([(i % 3, i) for i in range(10)], 2)
    for _ in range(3):
        r = r.reduceByKey(lambda a, b: a + b, 2)
    assert "plan-wide-depth" in rules(lint_plan(r))
    # a checkpoint pin on the path resets the count
    r2 = ctx.parallelize([(i % 3, i) for i in range(10)], 2)
    for i in range(3):
        r2 = r2.reduceByKey(lambda a, b: a + b, 2)
        if i == 1:
            r2._checkpoint_path = "/tmp/_fake_ck"     # pin marker only
    assert "plan-wide-depth" not in rules(lint_plan(r2))


def test_group_agg_rule_fires_when_rewrite_pinned_out(ctx):
    grouped = ctx.parallelize([(i % 3, i) for i in range(30)], 2) \
                 .groupByKey(2).cache()        # cache pin blocks rewrite
    m = grouped.mapValue(sum)
    from dpark_tpu import rdd as _rdd
    assert isinstance(m, _rdd.MappedValuesRDD)   # rewrite really declined
    assert "plan-group-agg" in rules(lint_plan(m))
    grouped.unpersist()


# ---------------------------------------------------------------------------
# closure rules (live callables)
# ---------------------------------------------------------------------------

def test_closure_rdd_capture_live(ctx):
    other = ctx.parallelize([1, 2, 3], 2)

    def bad(x):
        return (x, other.count())

    rep = lint_function(bad)
    assert "closure-rdd-capture" in rules(rep)
    [f] = [f for f in rep if f.rule == "closure-rdd-capture"]
    assert f.severity == "error"


def test_closure_context_capture_live(ctx):
    def bad(x):
        return ctx.parallelize([x]).count()

    assert "closure-rdd-capture" in rules(lint_function(bad))


def test_closure_clean_function_has_no_findings():
    def good(kv, m=7):
        return (kv[0] % m, kv[1])

    assert len(lint_function(good)) == 0


def test_preflight_warn_mode_never_blocks(ctx, monkeypatch):
    monkeypatch.setenv("DPARK_LINT", "warn")
    other = ctx.parallelize([1, 2, 3], 2)
    # the closure CAPTURES an rdd (error-severity finding) but warn
    # mode only logs: the job must still run on the local master
    r = ctx.parallelize([1, 2], 2).map(lambda x: (other, x + 3)[1])
    assert sorted(r.collect()) == [4, 5]


def test_preflight_off_mode_skips_all_work(ctx, monkeypatch):
    monkeypatch.setenv("DPARK_LINT", "off")
    r = ctx.parallelize([(1, (2, 3))], 1).reduceByKey(
        lambda a, b: max(a, b))
    assert preflight(r) is None


# ---------------------------------------------------------------------------
# closure rules (source-file mode) + the bad example
# ---------------------------------------------------------------------------

def test_bad_example_file_triggers_closure_rules():
    rep = lint_source(BAD_EXAMPLE)
    got = rules(rep)
    assert "closure-rdd-capture" in got
    assert "closure-unseeded-random" in got


def test_bad_example_plan_triggers_plan_rule(ctx):
    # the same plan shape the fixture writes down, built live: the
    # multi-leaf monoid reduce draws the plan-rule finding
    pairs = ctx.parallelize([(i % 5, (i, i * 2)) for i in range(100)], 4)
    worst = pairs.reduceByKey(lambda a, b: max(a, b))
    assert "monoid-multileaf" in rules(lint_plan(worst))


def test_source_mode_tracks_rdd_names():
    src = """
from dpark_tpu import DparkContext
ctx = DparkContext("local")
lookup = ctx.parallelize([(1, 2)], 2)
data = ctx.parallelize(range(10), 2)
out = data.map(lambda x: (x, lookup.count()))
safe = data.map(lambda x, lk=None: (x, lk))
"""
    rep = lint_source("inline.py", text=src)
    caps = [f for f in rep if f.rule == "closure-rdd-capture"]
    assert len(caps) == 1            # only the real capture


def test_source_mode_tracer_rules_escalate_for_tpu():
    src = """
from dpark_tpu import DparkContext
ctx = DparkContext("tpu")
data = ctx.parallelize(range(10), 2)
branchy = data.map(lambda x: 1 if x > 0 else 0)
"""
    host = [f for f in lint_source("inline.py", text=src)
            if f.rule == "closure-tracer-branch"]
    tpu = [f for f in lint_source("inline.py", text=src, tpu=True)
           if f.rule == "closure-tracer-branch"]
    assert host and host[0].severity == "info"
    assert tpu and tpu[0].severity == "warn"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _dlint(*args):
    env = dict(os.environ, PYTHONPATH=REPO, DPARK_PROGRESS="0")
    return subprocess.run(
        [sys.executable, "-m", "dpark_tpu.analysis", *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)


def test_cli_wordcount_example_is_clean():
    p = _dlint(os.path.join(REPO, "examples", "wordcount.py"))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "0 errors" in p.stderr


def test_cli_bad_example_fails_with_findings():
    p = _dlint(BAD_EXAMPLE, "--json")
    assert p.returncode == 1, p.stdout + p.stderr
    findings = json.loads(p.stdout)
    got = {f["rule"] for f in findings}
    assert "closure-rdd-capture" in got
    assert "closure-unseeded-random" in got


def test_monoid_multileaf_quiet_on_tuple_concat(ctx):
    # add over tuple values is legitimate per-key concatenation — all
    # masters agree on the result, so the rule must stay quiet
    import operator
    r = ctx.parallelize([(1, (2, 3)), (1, (4, 5))], 2) \
           .reduceByKey(operator.add)
    assert "monoid-multileaf" not in rules(lint_plan(r))
    assert sorted(r.collect()) == [(1, (2, 3, 4, 5))]


# ---------------------------------------------------------------------------
# host-fallback-group (ISSUE 4): why a grouped consumer left the array
# path, pre-flight
# ---------------------------------------------------------------------------

def _branchy_group_fn(vs):
    if len(vs) > 1:                     # data-dependent control flow
        return max(vs)
    return 0


def test_host_fallback_group_flags_untraceable_fn(ctx):
    from dpark_tpu import conf
    old = conf.GROUP_AGG_REWRITE
    conf.GROUP_AGG_REWRITE = False
    try:
        r = ctx.parallelize([(1, 2), (1, 3)], 2).groupByKey(2) \
               .mapValues(_branchy_group_fn)
        rep = lint_plan(r)
    finally:
        conf.GROUP_AGG_REWRITE = old
    assert "host-fallback-group" in rules(rep)


def test_host_fallback_group_quiet_on_traceable_and_provable(ctx):
    from dpark_tpu import conf
    old = conf.GROUP_AGG_REWRITE
    conf.GROUP_AGG_REWRITE = False
    try:
        sumsq = lambda vs: sum(v * v for v in vs)     # noqa: E731
        r = ctx.parallelize([(1, 2), (1, 3)], 2).groupByKey(2) \
               .mapValues(sumsq)
        assert "host-fallback-group" not in rules(lint_plan(r))
        r = ctx.parallelize([(1, 2), (1, 3)], 2).groupByKey(2) \
               .mapValues(sum)
        assert "host-fallback-group" not in rules(lint_plan(r))
    finally:
        conf.GROUP_AGG_REWRITE = old


def test_host_fallback_group_unsupported_value_pytree(ctx):
    from dpark_tpu import conf
    old = conf.GROUP_AGG_REWRITE
    conf.GROUP_AGG_REWRITE = False
    try:
        first = lambda vs: sum(v[0] for v in vs)      # noqa: E731
        r = ctx.parallelize([(1, (2, 3)), (1, (4, 5))], 2) \
               .groupByKey(2).mapValues(first)
        rep = lint_plan(r)
    finally:
        conf.GROUP_AGG_REWRITE = old
    [f] = [f for f in rep if f.rule == "host-fallback-group"]
    assert "value pytree" in f.message


def test_host_fallback_group_conf_disabled(ctx):
    from dpark_tpu import conf
    old_rw, old_sm = conf.GROUP_AGG_REWRITE, conf.SEG_MAP
    conf.GROUP_AGG_REWRITE = False
    conf.SEG_MAP = False
    try:
        sumsq = lambda vs: sum(v * v for v in vs)     # noqa: E731
        r = ctx.parallelize([(1, 2)], 2).groupByKey(2) \
               .mapValues(sumsq)
        rep = lint_plan(r)
    finally:
        conf.GROUP_AGG_REWRITE = old_rw
        conf.SEG_MAP = old_sm
    [f] = [f for f in rep if f.rule == "host-fallback-group"]
    assert "DPARK_SEG_MAP=0" in f.message
