"""Overlapped wave pipeline (ISSUE 2 tentpole): the streamed-shuffle
wave loop double-buffers device ingest, donates dead buffers to the
per-wave programs, defers host readback one wave, and spills through a
background writer — results must be BIT-IDENTICAL with the pipeline
and donation on vs off, cancellation mid-stream must not leak the
pipeline threads, and the per-wave metrics must show the overlap.

Runs on a 2-device sliced mesh ("tpu:2") so the suite works on small
containers where the full 8-device collective mesh wedges (see the
`mesh` marker in conftest)."""

import threading
import time

import numpy as np
import pytest

from dpark_tpu import Columns, conf


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def tiny_waves():
    old = (conf.STREAM_CHUNK_ROWS, conf.STREAM_PIPELINE_DEPTH,
           conf.DONATE_BUFFERS, conf.SPILL_WRITER)
    conf.STREAM_CHUNK_ROWS = 500
    yield
    (conf.STREAM_CHUNK_ROWS, conf.STREAM_PIPELINE_DEPTH,
     conf.DONATE_BUFFERS, conf.SPILL_WRITER) = old


def _pipeline_modes():
    # (depth, donate, spill_writer): full pipeline vs the serial
    # pre-pipeline configuration
    return [(1, True, True), (0, False, False)]


def _set_mode(depth, donate, writer):
    conf.STREAM_PIPELINE_DEPTH = depth
    conf.DONATE_BUFFERS = donate
    conf.SPILL_WRITER = writer


def _last_pipeline(ctx):
    best = None
    for rec in getattr(ctx.scheduler, "history", []):
        for st in rec.get("stage_info", []):
            if st.get("pipeline"):
                best = st["pipeline"]
    return best


def _mkdata(n=20000):
    i = np.arange(n, dtype=np.int64)
    return (i * 2654435761) % 997, i % 11


def test_streamed_combine_parity_pipeline_on_off(tctx2, tiny_waves):
    """Monoid reduceByKey through the combine stream: identical results
    (integer data: bit-identical) with the pipeline + donation on vs
    the serial loop."""
    keys, vals = _mkdata()
    got = {}
    for depth, donate, writer in _pipeline_modes():
        _set_mode(depth, donate, writer)
        got[depth] = dict(
            tctx2.parallelize(Columns(keys, vals), 2)
            .reduceByKey(lambda a, b: a + b, 2).collect())
        ex = tctx2.scheduler.executor
        assert any(s.get("pre_reduced")
                   for s in ex.shuffle_store.values()), "did not stream"
    assert got[1] == got[0]
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert got[1] == expect


def test_streamed_nocombine_parity_pipeline_on_off(tctx2, tiny_waves):
    """sortByKey through the spilled-run stream (r > mesh: the rid
    column rides the exchange): identical row ORDER and content with
    the pipeline on vs off."""
    rng = np.random.RandomState(17)
    keys = rng.randint(-10**6, 10**6, 20000).astype(np.int64)
    vals = np.arange(20000, dtype=np.int64)
    got = {}
    for depth, donate, writer in _pipeline_modes():
        _set_mode(depth, donate, writer)
        got[depth] = tctx2.parallelize(Columns(keys, vals), 2) \
            .sortByKey(numSplits=8).collect()
        ex = tctx2.scheduler.executor
        assert any("host_runs" in s
                   for s in ex.shuffle_store.values()), "did not spill"
    assert got[1] == got[0]
    assert [k for k, _ in got[1]] == sorted(keys.tolist())


def test_pipeline_overlap_beats_serial(tctx2, tiny_waves):
    """The acceptance observable at test scale: the pipelined run's
    host-observed device-idle fraction is strictly below the serial
    run's on the same workload, and the per-wave metrics are
    populated."""
    rng = np.random.RandomState(23)
    keys = rng.randint(0, 10**6, 24000).astype(np.int64)
    vals = np.arange(24000, dtype=np.int64)
    idle = {}
    for depth, donate, writer in _pipeline_modes():
        _set_mode(depth, donate, writer)
        tctx2.parallelize(Columns(keys, vals), 2) \
            .sortByKey(numSplits=8).collect()
        pipe = _last_pipeline(tctx2)
        assert pipe is not None
        assert pipe["waves"] > 1
        assert pipe["pipeline_depth"] == depth
        assert pipe["donated"] == donate
        for field in ("ingest_ms", "compute_ms", "exchange_ms",
                      "spill_ms", "device_idle_frac"):
            assert field in pipe
        idle[depth] = pipe["device_idle_frac"]
    assert idle[1] < idle[0], idle


def test_premerge_runs_in_background(tctx2, tiny_waves):
    """After a spilled stream finishes, the export premerger collapses
    every partition's runs into one key-sorted run without waiting for
    the first reduce fetch."""
    keys = np.arange(15000, dtype=np.int64) % 97
    vals = np.arange(15000, dtype=np.int64) % 13
    got = {k: sorted(v) for k, v in
           tctx2.parallelize(Columns(keys, vals), 2)
           .groupByKey(8).collect()}
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect.setdefault(k, []).append(v)
    assert got == {k: sorted(v) for k, v in expect.items()}
    ex = tctx2.scheduler.executor
    stores = [s for s in ex.shuffle_store.values() if "host_runs" in s]
    assert stores and stores[0].get("premerge") is not None
    pm = stores[0]["premerge"]
    if pm._thread is not None:
        pm._thread.join(timeout=10)
    for rid, paths in enumerate(stores[0]["host_runs"]):
        assert len(paths) <= 1, (rid, paths)
        got_paths, presorted = pm.ensure(rid)
        assert presorted


def _dpark_pipeline_threads():
    names = ("dpark-wave-prefetch", "dpark-wave-ingest",
             "dpark-spill-writer")
    return [t for t in threading.enumerate() if t.name in names]


def test_cancellation_mid_stream_shuts_down_threads(tctx2, tiny_waves):
    """A wave that fails mid-stream (here: a key colliding with the
    device padding sentinel, surfacing in the INGEST thread) must
    unwind the whole pipeline — tokenize prefetch, ingest thread,
    spill writer — without leaking threads or the spool directory,
    and the job must still answer through the object-path fallback."""
    import os
    from dpark_tpu.backend.tpu.layout import KEY_SENTINEL
    from dpark_tpu.env import env
    keys = np.arange(8000, dtype=np.int64) % 53
    keys[6500] = KEY_SENTINEL          # wave ~13 of 16 fails at ingest
    vals = np.ones(8000, dtype=np.int64)
    got = {k: sorted(v) for k, v in
           tctx2.parallelize(Columns(keys, vals), 2)
           .groupByKey(8).collect()}
    # object fallback computed the right answer (sentinel key included)
    assert got[int(KEY_SENTINEL)] == [1]
    assert sum(len(v) for v in got.values()) == 8000
    # no streamed store registered for the aborted array attempt
    ex = tctx2.scheduler.executor
    assert not any("host_runs" in s for s in ex.shuffle_store.values())
    # the aborted run's spool dir was cleaned up
    spool_root = os.path.join(env.workdir, "hbmruns")
    assert not os.path.isdir(spool_root) or not os.listdir(spool_root)
    # pipeline threads wind down (bounded poll: the prefetch stop
    # timeout is 0.5s per stage)
    deadline = time.time() + 8
    while time.time() < deadline and _dpark_pipeline_threads():
        time.sleep(0.1)
    assert not _dpark_pipeline_threads(), \
        [t.name for t in _dpark_pipeline_threads()]


def test_spill_writer_error_propagates():
    """A writer-thread failure surfaces on the wave loop's next put()
    or at finish(), never silently."""
    from dpark_tpu.backend.tpu.executor import _SpillWriter

    def bad_write(path, cols):
        raise OSError("disk gone")

    w = _SpillWriter(bad_write)
    w.put("/tmp/x1", [np.arange(3)])
    with pytest.raises(OSError):
        # the first write may still be in flight: poll put/finish
        for _ in range(50):
            w.put("/tmp/x2", [np.arange(3)])
            time.sleep(0.02)
        w.finish()
    w.abort()
    deadline = time.time() + 5
    while time.time() < deadline and w._thread.is_alive():
        time.sleep(0.05)
    assert not w._thread.is_alive()


def test_spill_writer_writes_and_finishes(tmp_path):
    from dpark_tpu.backend.tpu.executor import JAXExecutor, _SpillWriter
    w = _SpillWriter(JAXExecutor._write_run)
    paths = []
    for i in range(10):
        p = str(tmp_path / ("run-%d" % i))
        w.put(p, [np.arange(i + 1), np.ones(i + 1)])
        paths.append(p)
    w.finish()
    for i, p in enumerate(paths):
        cols = JAXExecutor._read_run(p)
        assert len(cols[0]) == i + 1
