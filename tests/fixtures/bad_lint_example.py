"""Purpose-built BAD example for the lint tests: every construct below
is an anti-pattern the analysis subsystem must catch.  dlint parses
this file (never executes it); tests/test_analysis.py also builds the
same plan shapes live and asserts the plan rules fire."""

import random

from dpark_tpu import DparkContext

ctx = DparkContext("local")
lookup = ctx.parallelize([(i, i * i) for i in range(10)], 2)
pairs = ctx.parallelize([(i % 5, (i, i * 2)) for i in range(100)], 4)

# monoid-multileaf: tuple values reduced with a bare max — the host
# compares tuples lexicographically, a per-leaf device monoid would mix
# leaves from different records (the round-5 silent-wrong-answer shape)
worst = pairs.reduceByKey(lambda a, b: max(a, b))

# closure-rdd-capture: the worker function reaches back into an RDD
tagged = worst.map(lambda kv: (kv[0], lookup.count()))

# closure-unseeded-random: retries/speculation see different data
noisy = tagged.map(lambda kv: (kv[0], random.random()))


def main():
    print(noisy.collect())


if __name__ == "__main__":
    main()
