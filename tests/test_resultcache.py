"""Shared-computation plane (ISSUE 18): the cross-tenant sub-plan
result cache.

The suite proves the contracts the plane makes:

* KEYING — an entry's identity covers the canonical plan signature,
  the resolved dtypes, and one source fingerprint per part file: v2
  files digest the footer stats (content-addressed — touching mtime
  does NOT drift them), v1 files fall back to (path, mtime_ns, size)
  so mutation ALWAYS means a miss, never a stale serve.
* SERVING — a repeated query plans into a CachedResult leaf with zero
  scan chunks and the bit-identical answer; a wider query whose
  mergeable group-aggregate was cached over a narrower contained
  filter merges the cached rows with a residual scan.
* HYGIENE — corrupt, truncated, or version-drifted disk entries are
  silent misses (the adapt-store contract); the memory tier evicts
  LRU-first under its byte budget.
* PARITY — off/mem/disk produce bit-identical results on a chaos
  (injected fetch-fault) job; the modes differ only in counters.
* TENANCY — tenants share by default; ``opt_out`` removes one from
  both directions; ``shared(False)`` pins one query out.
"""

import os
import pickle

import pytest

from dpark_tpu import adapt, conf, resultcache, service
from dpark_tpu.tabular import source_fingerprint, write_tabular


@pytest.fixture(autouse=True)
def _fresh_planes(tmp_path):
    """Every test gets its own adapt store and no installed result
    cache to start; no process-global server leaks."""
    adapt.configure(mode="observe", store_dir=str(tmp_path / "adapt"))
    resultcache.configure(mode="off")
    yield
    resultcache.configure(mode="off")
    adapt.configure()
    service.shutdown()


def _plane(tmp_path, mode="mem", **kw):
    return resultcache.configure(
        mode=mode, cache_dir=str(tmp_path / "rc"), **kw)


def _write(path, rows, fields, name="part-00000.tab",
           chunk_rows=500, version=2):
    os.makedirs(path, exist_ok=True)
    p = os.path.join(str(path), name)
    write_tabular(p, fields, rows, chunk_rows=chunk_rows,
                  version=version)
    return p


def _rows(n=4000):
    return [(i, i % 97, i % 50) for i in range(n)]


def _table(ctx, path):
    return ctx.tabular(str(path), ["t", "k", "a"]).asTable("events")


def _group(ctx, path, where="t >= 1000"):
    return _table(ctx, path).where(where).groupBy(
        "k", "sum(a) as s", "count(t) as c")


# ---------------------------------------------------------------------------
# modes and the off-mode seam
# ---------------------------------------------------------------------------

def test_mode_grammar(tmp_path):
    assert resultcache.configure(mode="off") is None
    assert not resultcache.active() and resultcache.plane() is None
    p = _plane(tmp_path, "mem")
    assert p.mode == "mem" and resultcache.active()
    assert resultcache.configure(mode="none") is None
    with pytest.raises(ValueError):
        resultcache.configure(mode="sometimes")


def test_off_seams_are_inert():
    resultcache.configure(mode="off")
    assert resultcache.stats() is None
    assert resultcache.probe(object()) is None
    assert resultcache.offer(object(), []) is False
    assert resultcache.opt_out("t") is False


# ---------------------------------------------------------------------------
# full hits: store on first run, serve the repeat with zero scan
# ---------------------------------------------------------------------------

def test_full_hit_round_trip(ctx, tmp_path):
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    cold = sorted(_group(ctx, path).collect())
    q2 = _group(ctx, path)
    warm = sorted(q2.collect())
    assert warm == cold
    pq = q2._planned()
    # the hit ran NO scan and the explain names what did not run
    assert pq.scan_stats == {}, pq.scan_stats
    assert "CachedResult" in pq.root.describe()
    st = resultcache.stats()
    assert st["hits"] == 1 and st["stores"] == 1
    assert st["misses"] == 1 and st["entries"] == 1


def test_scan_only_query_caches_too(ctx, tmp_path):
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    q = _table(ctx, path).where("t >= 3500")
    cold = sorted(q.collect())
    q2 = _table(ctx, path).where("t >= 3500")
    assert sorted(q2.collect()) == cold
    assert q2._planned().scan_stats == {}
    assert resultcache.stats()["hits"] == 1


def test_in_memory_source_never_cached(ctx, tmp_path):
    """parallelize-backed tables mutate invisibly — no fingerprint,
    no entry, not even a recorded miss."""
    _plane(tmp_path)
    rows = [("a", 1), ("b", 2), ("a", 3)]
    t = ctx.parallelize(rows, 2).asTable("k v", name="m")
    t.groupBy("k", "sum(v) as s").collect()
    st = resultcache.stats()
    assert st["stores"] == 0 and st["misses"] == 0


# ---------------------------------------------------------------------------
# fingerprints: v2 content-addressed, v1 mtime+size fallback
# (satellites 1 and 3)
# ---------------------------------------------------------------------------

def test_fingerprint_versions(tmp_path):
    rows = _rows(1200)
    p2 = _write(tmp_path / "v2", rows, ["t", "k", "a"])
    p1 = _write(tmp_path / "v1", rows, ["t", "k", "a"], version=1)
    f2 = source_fingerprint(p2)
    f1 = source_fingerprint(p1)
    assert f2[0] == "v2" and f1[0] == "v1"
    # v1 falls back to (path, mtime_ns, size)
    assert f1[1] == p1 and f1[3] == os.stat(p1).st_size
    # missing file: a distinct sentinel, not an error
    assert source_fingerprint(str(tmp_path / "ghost"))[0] == "v?"


def test_mixed_v1_v2_table_caches_and_invalidates(ctx, tmp_path):
    """A table directory mixing a v2 part with a v1 (stat-less) part
    still caches; TOUCHING the v1 part (mtime drift, same bytes)
    invalidates, while touching the v2 part does not — its
    fingerprint is content-addressed."""
    _plane(tmp_path)
    path = tmp_path / "mix"
    rows = _rows()
    _write(path, rows[:2000], ["t", "k", "a"], "part-00000.tab")
    _write(path, rows[2000:], ["t", "k", "a"], "part-00001.tab",
           version=1)
    cold = sorted(_group(ctx, path).collect())
    assert sorted(_group(ctx, path).collect()) == cold
    assert resultcache.stats()["hits"] == 1
    # v2 touch: content unchanged -> fingerprint unchanged -> hit
    os.utime(os.path.join(str(path), "part-00000.tab"))
    assert sorted(_group(ctx, path).collect()) == cold
    assert resultcache.stats()["hits"] == 2
    # v1 touch: the stat fallback drifts -> miss (and a re-store)
    os.utime(os.path.join(str(path), "part-00001.tab"))
    q = _group(ctx, path)
    assert sorted(q.collect()) == cold
    assert resultcache.stats()["hits"] == 2
    assert q._planned().scan_stats.get("chunks_total"), \
        q._planned().scan_stats


def test_mutation_means_miss(ctx, tmp_path):
    """Rewriting a part file with DIFFERENT rows must serve the new
    answer — the v2 stats digest drifts without reading a data
    byte."""
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    cold = sorted(_group(ctx, path).collect())
    _write(path, [(t, k, a * 2) for t, k, a in _rows()],
           ["t", "k", "a"])
    fresh = sorted(_group(ctx, path).collect())
    assert fresh != cold
    assert {r.k: r.s for r in fresh} == \
        {r.k: r.s * 2 for r in cold}
    assert resultcache.stats()["hits"] == 0


# ---------------------------------------------------------------------------
# chaos parity: off/mem/disk agree bit-for-bit (satellite 3)
# ---------------------------------------------------------------------------

def test_off_mem_disk_chaos_parity(tmp_path):
    from dpark_tpu import DparkContext, faults
    path = tmp_path / "tab"
    _write(path, _rows(8000), ["t", "k", "a"], chunk_rows=1000)
    results, stats = {}, {}
    for run, mode in (("off", "off"), ("mem", "mem"),
                      ("disk", "disk"), ("disk-warm", "disk")):
        _plane(tmp_path, mode)
        faults.configure("shuffle.fetch:p=0.2,seed=7,times=3")
        c = DparkContext("tpu:2")
        c.start()
        try:
            q = _group(c, path)
            results[run] = sorted(q.collect())
            # a second identical query inside the same run must hit
            if mode != "off":
                results[run + "/2"] = sorted(_group(c, path).collect())
        finally:
            c.stop()
            faults.configure(None)
        stats[run] = resultcache.stats()
    assert results["off"] == results["mem"] == results["disk"] \
        == results["disk-warm"]
    assert results["mem"] == results["mem/2"] == results["disk/2"] \
        == results["disk-warm/2"]
    assert stats["off"] is None
    assert stats["mem"]["hits"] == 1 and stats["mem"]["stores"] == 1
    assert stats["disk"]["disk_stores"] == 1
    # the fourth pass reconfigured a FRESH plane on the same dir: its
    # memory tier starts empty and the hit comes off disk
    assert stats["disk-warm"]["disk_loads"] == 1
    assert stats["disk-warm"]["load_errors"] == 0


# ---------------------------------------------------------------------------
# memory tier: size-budgeted LRU
# ---------------------------------------------------------------------------

def _ent(nbytes, tenant="local"):
    return {"rows": [], "fields": ["x"], "nbytes": nbytes,
            "meta": None, "group_sig": None, "tenant": tenant}


def test_lru_eviction_under_budget(tmp_path):
    p = _plane(tmp_path, "mem", budget_bytes=1000)
    p._insert("k1", _ent(600), write_disk=False)
    p._insert("k2", _ent(600), write_disk=False)
    st = p.stats()
    assert st["evictions"] == 1 and st["entries"] == 1
    assert "k2" in p._mem and "k1" not in p._mem
    assert st["bytes"] <= 1000


def test_lru_touch_on_get(tmp_path):
    p = _plane(tmp_path, "mem", budget_bytes=1000)
    p._insert("k1", _ent(400), write_disk=False)
    p._insert("k2", _ent(400), write_disk=False)
    assert p.get("k1") is not None      # k1 becomes MRU
    p._insert("k3", _ent(400), write_disk=False)
    assert "k1" in p._mem and "k2" not in p._mem


def test_oversize_result_never_stored(ctx, tmp_path):
    _plane(tmp_path, "mem", budget_bytes=64)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    _group(ctx, path).collect()
    st = resultcache.stats()
    assert st["oversize"] == 1 and st["stores"] == 0


# ---------------------------------------------------------------------------
# disk tier: round trip, defect hygiene, boot preload
# ---------------------------------------------------------------------------

def test_disk_round_trip(tmp_path):
    p = _plane(tmp_path, "disk")
    blob = pickle.dumps((["x"], [(1, 2)], None), protocol=2)
    ent = {"rows": [(1, 2)], "fields": ["x"], "nbytes": len(blob),
           "meta": None, "group_sig": None, "tenant": "t-a"}
    p._store_entry("kk", blob, ent)
    got = p._load_entry("kk")
    assert got is not None
    assert got["rows"] == [(1, 2)] and got["tenant"] == "t-a"
    assert p.index()["kk"]["nbytes"] == len(blob)


@pytest.mark.parametrize("defect", ["flip", "truncate", "garbage"])
def test_corrupt_entries_fall_back_silently(tmp_path, defect):
    p = _plane(tmp_path, "disk")
    blob = pickle.dumps((["x"], [(1, 2)], None), protocol=2)
    p._store_entry("kk", blob, _ent(len(blob)))
    ep = p._entry_path("kk")
    raw = open(ep, "rb").read()
    if defect == "flip":
        raw = raw[:-3] + bytes([raw[-3] ^ 0xFF]) + raw[-2:]
    elif defect == "truncate":
        raw = raw[:len(raw) // 2]
    else:
        raw = b"not an entry at all"
    with open(ep, "wb") as f:
        f.write(raw)
    assert p._load_entry("kk") is None
    assert p.stats()["load_errors"] == 1


def test_version_drift_skips(tmp_path, monkeypatch):
    p = _plane(tmp_path, "disk")
    blob = pickle.dumps((["x"], [(1, 2)], None), protocol=2)
    monkeypatch.setattr(resultcache, "FORMAT", "dpark-rc-0")
    p._store_entry("kk", blob, _ent(len(blob)))
    monkeypatch.undo()
    assert p._load_entry("kk") is None
    assert p.stats()["version_skips"] == 1
    # old-format index lines skip too
    assert p.index() == {}


def test_boot_preloads_hottest_first(tmp_path):
    blob = pickle.dumps((["x"], [(1, 2)], None), protocol=2)
    budget = len(blob) * 3              # cap (= budget//2) fits ONE
    p = _plane(tmp_path, "disk", budget_bytes=budget)
    p._store_entry("cold-key", blob, _ent(len(blob)))
    p._store_entry("hot-key", blob, _ent(len(blob)))
    adapt.record_reuse("hot-key", hits=3)
    # a restarted server: fresh plane on the same dir
    p2 = _plane(tmp_path, "disk", budget_bytes=budget)
    summary = p2.boot()
    assert summary["entries"] == 2 and summary["preloaded"] == 1
    assert "hot-key" in p2._mem and "cold-key" not in p2._mem


def test_disk_hit_survives_restart(ctx, tmp_path):
    _plane(tmp_path, "disk")
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    cold = sorted(_group(ctx, path).collect())
    # restart: fresh plane, same dir, boot back the stored entry
    p2 = _plane(tmp_path, "disk")
    assert p2.boot()["preloaded"] == 1
    q = _group(ctx, path)
    assert sorted(q.collect()) == cold
    assert q._planned().scan_stats == {}
    assert resultcache.stats()["hits"] == 1


# ---------------------------------------------------------------------------
# partial-aggregate reuse
# ---------------------------------------------------------------------------

def test_partial_merge_serves_wider_query(ctx, tmp_path):
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"], chunk_rows=500)
    q_narrow = _group(ctx, path, "t >= 500")
    q_narrow.collect()                  # caches the 7/8-chunk answer
    q_wide = _group(ctx, path, "t >= 0")
    got = sorted(q_wide.collect())
    st = resultcache.stats()
    assert st["partial_hits"] == 1, st
    scan = q_wide._planned().scan_stats
    # the residual scan covers t <= 499 only: one chunk read
    assert scan["chunks_total"] - scan["chunks_skipped"] == 1, scan
    resultcache.configure(mode="off")
    assert got == sorted(_group(ctx, path, "t >= 0").collect())


def test_partial_merge_all_mergeable_kinds(ctx, tmp_path):
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"], chunk_rows=500)

    def q(where):
        return _table(ctx, path).where(where).groupBy(
            "k", "sum(a) as s", "count(t) as c", "min(a) as mn",
            "max(a) as mx")

    q("t >= 600").collect()
    got = sorted(q("t >= 0").collect())
    assert resultcache.stats()["partial_hits"] == 1
    resultcache.configure(mode="off")
    assert got == sorted(q("t >= 0").collect())


def test_avg_is_not_partial_mergeable(ctx, tmp_path):
    """avg finalizes s/c — its finished rows cannot merge.  Full
    caching still applies; the partial probe must not."""
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])

    def q(where):
        return _table(ctx, path).where(where).groupBy(
            "k", "avg(a) as av")

    q("t >= 500").collect()
    got = sorted(q("t >= 0").collect())
    st = resultcache.stats()
    assert st["partial_hits"] == 0 and st["stores"] == 2
    resultcache.configure(mode="off")
    assert got == sorted(q("t >= 0").collect())


def test_equivalent_ranges_serve_as_full_hit(ctx, tmp_path):
    """`t > 499` and `t >= 500` differ as text (different exact key)
    but describe the same region — the cached rows ARE the answer."""
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    cold = sorted(_group(ctx, path, "t >= 500").collect())
    q = _group(ctx, path, "t > 499")
    assert sorted(q.collect()) == cold
    st = resultcache.stats()
    assert st["hits"] == 1 and q._planned().scan_stats == {}


def test_disjoint_or_wider_cache_never_merges(ctx, tmp_path):
    """A cached entry WIDER than (or overlapping) the new query must
    not partial-serve — only contained boxes merge."""
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    _group(ctx, path, "t >= 100").collect()
    got = sorted(_group(ctx, path, "t >= 200").collect())
    st = resultcache.stats()
    assert st["partial_hits"] == 0 and st["hits"] == 0
    resultcache.configure(mode="off")
    assert got == sorted(_group(ctx, path, "t >= 200").collect())


def test_merge_group_rows_units():
    merged = resultcache.merge_group_rows(
        [(1, 10.0, 2, 5, 9), (2, 4.0, 1, 7, 7)],
        [(1, 1.0, 1, 3, 11), (3, 2.0, 1, 0, 0)],
        nk=1, kinds=("sum", "count", "min", "max"))
    assert merged == [(1, 11.0, 3, 3, 11), (2, 4.0, 1, 7, 7),
                      (3, 2.0, 1, 0, 0)]


def test_interval_helpers():
    c = resultcache._interval_contains
    assert c((None, None), (5, 10))
    assert c((0, 10), (0, 10)) and not c((0, 10), (0, 11))
    assert not c((5, None), (None, 10))
    r = resultcache._residual_intervals
    assert r((0, 100), (50, 100)) == [(0, 49)]
    assert r((None, None), (50, None)) == [(None, 49)]
    assert r((0, 100), (20, 80)) == [(0, 19), (81, 100)]
    assert r((5, 9), (5, 9)) == []


# ---------------------------------------------------------------------------
# tenancy: opt-out in both directions, per-query shared(False)
# ---------------------------------------------------------------------------

def test_tenant_opt_out_both_directions(ctx, tmp_path):
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    resultcache.opt_out("tenant-z")
    with resultcache.tenant("tenant-z"):
        _group(ctx, path).collect()     # neither reads nor stores
    st = resultcache.stats()
    assert st["opt_outs"] == 1 and st["stores"] == 0
    with resultcache.tenant("tenant-y"):
        cold = sorted(_group(ctx, path).collect())
    assert resultcache.stats()["stores"] == 1
    with resultcache.tenant("tenant-z"):
        q = _group(ctx, path)
        assert sorted(q.collect()) == cold
        assert q._planned().scan_stats != {}    # scanned, no serve
    assert resultcache.stats()["hits"] == 0
    # re-admission restores sharing
    resultcache.opt_out("tenant-z", flag=False)
    with resultcache.tenant("tenant-z"):
        _group(ctx, path).collect()
    assert resultcache.stats()["hits"] == 1


def test_shared_false_pins_one_query_out(ctx, tmp_path):
    _plane(tmp_path)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    q = _group(ctx, path).shared(False)
    cold = sorted(q.collect())
    st = resultcache.stats()
    assert st["stores"] == 0 and st["misses"] == 0
    assert sorted(_group(ctx, path).collect()) == cold  # stores now
    q3 = _group(ctx, path).shared(False)
    assert sorted(q3.collect()) == cold
    assert q3._planned().scan_stats != {}       # planned past the hit
    assert resultcache.stats()["hits"] == 0


def test_client_scheduler_share_results_opt_out(tmp_path):
    p = _plane(tmp_path)
    srv = service.get_server("local")
    service.ClientScheduler(srv, client="t-priv", share_results=False)
    assert "t-priv" in p._opt_out
    service.ClientScheduler(srv, client="t-priv", share_results=True)
    assert "t-priv" not in p._opt_out


# ---------------------------------------------------------------------------
# the repeated-subplan lint rule (ISSUE 18 satellite)
# ---------------------------------------------------------------------------

def _lineage_of(*queries):
    from dpark_tpu.query import logical
    out = []
    for q in queries:
        out.extend(logical.iter_plan(q._planned().root))
    return out


def test_repeated_subplan_flags_distinct_duplicates(ctx, tmp_path):
    from dpark_tpu.analysis.plan_rules import (Report,
                                               _rule_repeated_subplan)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    q1 = _group(ctx, path)
    q2 = _group(ctx, path)
    rep = Report()
    _rule_repeated_subplan(_lineage_of(q1, q2), rep)
    hits = [f for f in rep.findings if f.rule == "repeated-subplan"]
    # maximal-only: the duplicated Filter inside the duplicated
    # GroupAgg is the SAME finding, not a second one
    assert len(hits) == 1, [f.message for f in rep.findings]
    assert "GroupAgg" in hits[0].message


def test_repeated_subplan_shared_objects_clean(ctx, tmp_path):
    from dpark_tpu.analysis.plan_rules import (Report,
                                               _rule_repeated_subplan)
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    q = _group(ctx, path)
    rep = Report()
    # the same plan walked twice is ONE evaluation (same object ids)
    _rule_repeated_subplan(_lineage_of(q, q), rep)
    assert not [f for f in rep.findings
                if f.rule == "repeated-subplan"]


def test_repeated_subplan_bare_scans_clean(ctx, tmp_path):
    from dpark_tpu.analysis.plan_rules import (Report,
                                               _rule_repeated_subplan)
    from dpark_tpu.query import logical
    path = tmp_path / "tab"
    _write(path, _rows(), ["t", "k", "a"])
    src = _table(ctx, path)
    pq = src.where("t >= 0")._planned()
    scan = pq.segs[0].scan
    rep = Report()
    _rule_repeated_subplan(
        [logical.Scan(scan.source, list(scan.fields), "events"),
         logical.Scan(scan.source, list(scan.fields), "events")],
        rep)
    assert not [f for f in rep.findings
                if f.rule == "repeated-subplan"]
