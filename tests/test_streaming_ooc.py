"""Out-of-core streaming beyond monoid reduceByKey (SURVEY.md 7.2 item
4): sortByKey (range exchange, spilled sorted runs), groupByKey
(spill-to-disk runs + lazy heap merge), and text-source wave ingest.
Waves are forced tiny so a few thousand rows exercise the full pipeline;
each test asserts parity with the local master and that the spilled
stores hold (almost) nothing in HBM."""

import numpy as np
import pytest

from dpark_tpu import Columns, conf

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def tiny_waves():
    old = (conf.STREAM_CHUNK_ROWS, conf.STREAM_TEXT_BYTES)
    conf.STREAM_CHUNK_ROWS = 500
    conf.STREAM_TEXT_BYTES = 4000
    yield
    conf.STREAM_CHUNK_ROWS, conf.STREAM_TEXT_BYTES = old


def _spilled(tctx):
    ex = tctx.scheduler.executor
    return any("host_runs" in s for s in ex.shuffle_store.values())


def test_streamed_sortbykey(tctx, tiny_waves):
    rng = np.random.RandomState(5)
    keys = rng.randint(-10**6, 10**6, 20000).astype(np.int64)
    vals = np.arange(20000, dtype=np.int64)
    got = tctx.parallelize(Columns(keys, vals), 8) \
              .sortByKey(numSplits=8).collect()
    assert _spilled(tctx)
    assert [k for k, _ in got] == sorted(keys.tolist())
    # full row multiset parity
    assert sorted(got) == sorted(zip(keys.tolist(), vals.tolist()))


def test_streamed_sortbykey_descending(tctx, tiny_waves):
    keys = (np.arange(6000, dtype=np.int64) * 7919) % 1000
    vals = np.ones(6000, dtype=np.int64)
    got = tctx.parallelize(Columns(keys, vals), 8) \
              .sortByKey(ascending=False, numSplits=4).collect()
    assert [k for k, _ in got] == sorted(keys.tolist(), reverse=True)


def test_streamed_groupbykey(tctx, tiny_waves):
    n = 15000
    keys = (np.arange(n, dtype=np.int64) * 31) % 97
    vals = np.arange(n, dtype=np.int64) % 11
    got = {k: sorted(v) for k, v in
           tctx.parallelize(Columns(keys, vals), 8)
           .groupByKey(8).collect()}
    assert _spilled(tctx)
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect.setdefault(k, []).append(v)
    assert got == {k: sorted(v) for k, v in expect.items()}


def test_streamed_partitionby_then_reduce(tctx, tiny_waves):
    n = 8000
    keys = np.arange(n, dtype=np.int64) % 53
    vals = np.ones(n, dtype=np.int64)
    r = tctx.parallelize(Columns(keys, vals), 8).partitionBy(8)
    got = {}
    for k, v in r.collect():
        got[k] = got.get(k, 0) + v
    assert got == {k: n // 53 + (1 if k < n % 53 else 0)
                   for k in range(53)}


def test_streamed_text_wordcount(tctx, tiny_waves, tmp_path):
    import random
    rng = random.Random(9)
    words = ["aa", "bb", "cc", "dd", "ee"]
    p = str(tmp_path / "big.txt")
    with open(p, "w") as f:
        for _ in range(3000):
            f.write(" ".join(rng.choices(words, k=6)) + "\n")

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=2000)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda a, b: a + b, 8).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    # the monoid stream leaves a pre-reduced store, not a full dataset
    ex = tctx.scheduler.executor
    assert any(s.get("pre_reduced") for s in ex.shuffle_store.values())


def test_streamed_text_groupbykey(tctx, tiny_waves, tmp_path):
    p = str(tmp_path / "g.txt")
    with open(p, "w") as f:
        for i in range(2000):
            f.write("w%d x\n" % (i % 7))

    def run(ctx):
        return {k: sorted(v) for k, v in
                ctx.textFile(p, splitSize=1500)
                .flatMap(lambda line: line.split())
                .map(lambda w: (w, len(w)))
                .groupByKey(4).collect()}

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert _spilled(tctx)


def test_streamed_text_sortbykey(tctx, tiny_waves, tmp_path):
    """File-sourced numeric sort: text plan with a RANGE partitioner,
    streamed through spilled runs."""
    p = str(tmp_path / "nums.txt")
    rng = np.random.RandomState(3)
    nums = rng.randint(0, 10**6, 5000)
    with open(p, "w") as f:
        for x in nums.tolist():
            f.write("%d\n" % x)

    def run(ctx):
        return ctx.textFile(p, splitSize=3000) \
                  .map(lambda l: (int(l), 1)).sortByKey(numSplits=4) \
                  .collect()

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert [k for k, _ in got] == [k for k, _ in expect]
    assert sorted(got) == sorted(expect)


def test_spool_cleanup_on_drop(tctx, tiny_waves):
    import os
    keys = np.arange(5000, dtype=np.int64) % 17
    vals = np.ones(5000, dtype=np.int64)
    r = tctx.parallelize(Columns(keys, vals), 8).groupByKey(8)
    r.collect()
    ex = tctx.scheduler.executor
    spools = [s["spool_dir"] for s in ex.shuffle_store.values()
              if s.get("spool_dir")]
    assert spools and all(os.path.isdir(d) for d in spools)
    for sid in list(ex.shuffle_store):
        ex.drop_shuffle(sid)
    assert not any(os.path.isdir(d) for d in spools)


def test_streamed_generic_combiner(tctx, tiny_waves):
    """A traceable NON-monoid merge (tuple-wise sums) streams too, via
    the segmented associative scan."""
    n = 12000
    keys = (np.arange(n, dtype=np.int64) * 13) % 37
    vals = np.arange(n, dtype=np.int64) % 9
    got = dict(tctx.parallelize(Columns(keys, vals), 8)
               .mapValue(lambda v: (v, 1))
               .reduceByKey(lambda a, b: (a[0] + b[0], a[1] + b[1]), 8)
               .collect())
    ex = tctx.scheduler.executor
    assert any(s.get("pre_reduced")
               for s in ex.shuffle_store.values()), "did not stream"
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        s, c = expect.get(k, (0, 0))
        expect[k] = (s + v, c + 1)
    assert got == expect


def test_logical_partitions_beyond_mesh(tctx, tiny_waves):
    """r > ndev: the spilled-run stream carries the LOGICAL partition id
    through the exchange, so big sorts/groups can use many small reduce
    partitions (bounded reduce memory) instead of mesh-sized ones."""
    rng = np.random.RandomState(11)
    keys = rng.randint(0, 10**6, 20000).astype(np.int64)
    vals = np.arange(20000, dtype=np.int64)
    got = tctx.parallelize(Columns(keys, vals), 8) \
              .sortByKey(numSplits=32).collect()
    assert _spilled(tctx)
    store = [s for s in tctx.scheduler.executor.shuffle_store.values()
             if "host_runs" in s][0]
    assert len(store["host_runs"]) == 32
    assert [k for k, _ in got] == sorted(keys.tolist())
    assert sorted(got) == sorted(zip(keys.tolist(), vals.tolist()))

    g = {k: sorted(v) for k, v in
         tctx.parallelize(Columns(keys % 101, vals), 8)
         .groupByKey(64).collect()}
    expect = {}
    for k, v in zip((keys % 101).tolist(), vals.tolist()):
        expect.setdefault(k, []).append(v)
    assert g == {k: sorted(v) for k, v in expect.items()}


def _spilled_rows(tctx):
    """Total rows across all spilled run files (column lengths)."""
    from dpark_tpu.backend.tpu.executor import JAXExecutor
    total = 0
    for s in tctx.scheduler.executor.shuffle_store.values():
        for paths in s.get("host_runs", []):
            for p in paths:
                cols = JAXExecutor._read_run(p)
                total += len(cols[0])
    return total


def test_traceable_monoid_beyond_mesh(tctx, tiny_waves):
    """r > ndev with a classified monoid merge rides the spilled-run
    stream; each wave pre-reduces per (rid, key) ON DEVICE before
    spilling, so runs hold one combiner per distinct key per wave, not
    every row (previously this fell to the object path)."""
    n = 20000
    i = np.arange(n, dtype=np.int64)
    keys = (i * 13) % 37
    vals = i % 7
    got = dict(tctx.parallelize(Columns(keys, vals), 8)
               .reduceByKey(lambda a, b: a + b, 24).collect())
    assert _spilled(tctx)
    store = [s for s in tctx.scheduler.executor.shuffle_store.values()
             if "host_runs" in s][0]
    assert store["host_combine"]
    # 5 waves x <=37 distinct keys: far fewer spilled rows than input
    assert _spilled_rows(tctx) <= 37 * 8, _spilled_rows(tctx)
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert got == expect


def test_traceable_generic_merge_beyond_mesh(tctx, tiny_waves):
    """A traceable NON-monoid merge (tuple-wise sums) with r > ndev:
    pre-reduce runs through the segmented associative scan."""
    n = 16000
    i = np.arange(n, dtype=np.int64)
    keys = (i * 31) % 101
    vals = i % 9
    got = dict(tctx.parallelize(Columns(keys, vals), 8)
               .mapValue(lambda v: (v, 1))
               .reduceByKey(lambda a, b: (a[0] + b[0], a[1] + b[1]), 32)
               .collect())
    assert _spilled(tctx)
    assert _spilled_rows(tctx) <= 101 * 8, _spilled_rows(tctx)
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        s, c = expect.get(k, (0, 0))
        expect[k] = (s + v, c + 1)
    assert got == expect


def test_traceable_merge_beyond_mesh_text(tctx, tiny_waves, tmp_path):
    """Text wordcount with r > ndev streams through the spilled runs
    with device pre-reduce, with exact parity vs the local master."""
    import random
    rng = random.Random(21)
    words = ["w%d" % d for d in range(23)]
    p = str(tmp_path / "wide.txt")
    with open(p, "w") as f:
        for _ in range(2500):
            f.write(" ".join(rng.choices(words, k=6)) + "\n")

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=1800)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda a, b: a + b, 20).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    assert _spilled(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_spilled_rerun_keeps_new_spool(tctx, tiny_waves):
    """Re-running a spilled map stage while the OLD store is still
    registered must not delete the new run files (per-run spool dirs)."""
    from dpark_tpu.env import env
    keys = np.arange(4000, dtype=np.int64) % 13
    vals = np.arange(4000, dtype=np.int64) % 7
    r = tctx.parallelize(Columns(keys, vals), 8).groupByKey(8)
    first = {k: sorted(v) for k, v in r.collect()}
    # force a full map-stage re-run with the old store still present
    for stage in tctx.scheduler.shuffle_to_stage.values():
        stage.output_locs = [None] * len(stage.output_locs)
    env.map_output_tracker.locs.clear()
    second = {k: sorted(v) for k, v in r.collect()}
    assert second == first


def test_streamed_store_recovery_after_drop(tctx, tiny_waves):
    """Dropping the spilled store recomputes through lineage."""
    keys = np.arange(6000, dtype=np.int64) % 29
    vals = np.arange(6000, dtype=np.int64) % 5
    r = tctx.parallelize(Columns(keys, vals), 8).sortByKey(numSplits=4)
    first = r.collect()
    ex = tctx.scheduler.executor
    for sid in list(ex.shuffle_store):
        ex.drop_shuffle(sid)
    second = r.collect()
    # key order is the contract; equal-key value order may differ
    # between the streamed and the recovered path
    assert [k for k, _ in second] == [k for k, _ in first]
    assert sorted(second) == sorted(first)
