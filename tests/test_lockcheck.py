"""Concurrency sanitizer plane (ISSUE 16): static rule families,
dynamic lock-order cycle detection, the PR 3 export-deadlock regression
shape, off-mode inertness, and record-vs-off parity."""

import ast
import threading

import pytest

from dpark_tpu import locks
from dpark_tpu.analysis.concurrency import (ConcurrencyPass,
                                            check_plane_seam)
from dpark_tpu.analysis.report import Report


def _run_pass(tmp_path, sources):
    p = ConcurrencyPass(root=str(tmp_path))
    for name, src in sources.items():
        f = tmp_path / name
        f.write_text(src)
        p.add_source(str(f))
    rep = Report()
    p.finish(rep)
    return rep


def _rules(rep, rule):
    return [f for f in rep if f.rule == rule]


# ---------------------------------------------------------------------------
# static rules on synthetic modules
# ---------------------------------------------------------------------------

class TestStaticRules:
    def test_lexical_inversion_is_a_cycle(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def f():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def g():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n")})
        found = _rules(rep, "lock-order-cycle")
        assert len(found) == 1
        assert "m.A" in found[0].message and "m.B" in found[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def f():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def g():\n"
            "    with A, B:\n"
            "        pass\n")})
        assert not _rules(rep, "lock-order-cycle")

    def test_interprocedural_cycle_through_a_call(self, tmp_path):
        # f: A -> call g (acquires B); h: B -> call k (acquires A)
        rep = _run_pass(tmp_path, {"m.py": (
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def g():\n"
            "    with B:\n"
            "        pass\n"
            "def f():\n"
            "    with A:\n"
            "        g()\n"
            "def k():\n"
            "    with A:\n"
            "        pass\n"
            "def h():\n"
            "    with B:\n"
            "        k()\n")})
        assert len(_rules(rep, "lock-order-cycle")) == 1

    def test_named_lock_literal_is_the_node_name(self, tmp_path):
        # named_lock("x") merges with the DYNAMIC graph's node "x"
        rep = _run_pass(tmp_path, {"m.py": (
            "from dpark_tpu import locks\n"
            "A = locks.named_lock('pool.a')\n"
            "B = locks.named_lock('pool.b')\n"
            "def f():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n"
            "def g():\n"
            "    with B:\n"
            "        with A:\n"
            "            pass\n")})
        found = _rules(rep, "lock-order-cycle")
        assert len(found) == 1
        assert "pool.a" in found[0].message

    def test_blocking_under_mesh_lock(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "M = _MeshLock()\n"
            "def f(sock):\n"
            "    with M:\n"
            "        sock.recv(1024)\n")})
        found = _rules(rep, "blocking-under-lock")
        assert len(found) == 1
        assert "recv" in found[0].message

    def test_blocking_reached_through_a_call(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "M = _MeshLock()\n"
            "def leaf(path):\n"
            "    return open(path)\n"
            "def f(path):\n"
            "    with M:\n"
            "        leaf(path)\n")})
        found = _rules(rep, "blocking-under-lock")
        assert found and "leaf" in found[0].message

    def test_blocking_without_mesh_lock_is_clean(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "import threading\n"
            "L = threading.Lock()\n"
            "def f(sock):\n"
            "    with L:\n"
            "        sock.recv(1024)\n")})
        assert not _rules(rep, "blocking-under-lock")

    def test_unbounded_wait_shapes(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "def f(q, d, cv):\n"
            "    q.get()\n"                     # flagged
            "    q.get(timeout=1)\n"            # bounded
            "    d.get('key')\n"                # dict.get
            "    cv.wait()\n"                   # flagged
            "    cv.wait(0.5)\n"                # bounded
            "    ', '.join(['a'])\n")})         # str.join
        found = _rules(rep, "unbounded-wait")
        assert len(found) == 2
        kinds = sorted(f.message.split(":")[0] for f in found)
        assert "queue .get() without timeout" in kinds[1]
        assert ".wait() without timeout" in kinds[0]

    def test_thread_leak(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "import threading\n"
            "def f(work):\n"
            "    t = threading.Thread(target=work)\n"
            "    t.start()\n")})
        assert len(_rules(rep, "thread-leak")) == 1

    def test_daemon_or_joined_thread_is_clean(self, tmp_path):
        rep = _run_pass(tmp_path, {"m.py": (
            "import threading\n"
            "def f(work):\n"
            "    t = threading.Thread(target=work, daemon=True)\n"
            "    t.start()\n"
            "def g(work):\n"
            "    u = threading.Thread(target=work)\n"
            "    u.start()\n"
            "    u.join(timeout=5)\n")})
        assert not _rules(rep, "thread-leak")


# ---------------------------------------------------------------------------
# plane-contract rule
# ---------------------------------------------------------------------------

class TestPlaneContract:
    def test_good_seams_both_forms(self):
        src = ("_PLANE = None\n"
               "def direct(x):\n"
               "    if _PLANE is None:\n"
               "        return x\n"
               "    return _PLANE.f(x)\n"
               "def bound(x):\n"
               "    plane = _PLANE\n"
               "    if plane is None:\n"
               "        return x\n"
               "    return plane.f(x)\n"
               "def guarded(x):\n"
               "    plane = _PLANE\n"
               "    if plane is not None:\n"
               "        plane.f(x)\n"
               "    return x\n")
        tree = ast.parse(src)
        for fn in ("direct", "bound", "guarded"):
            assert check_plane_seam(tree, fn, "_PLANE") is None, fn

    def test_direct_form_may_reload_on_path(self):
        # the contract is about the OFF path: a second load after the
        # is-None guard returned runs only with the plane on
        tree = ast.parse(
            "_PLANE = None\n"
            "def f(x):\n"
            "    if _PLANE is None:\n"
            "        return x\n"
            "    return _PLANE.g(x)\n")
        assert check_plane_seam(tree, "f", "_PLANE") is None

    def test_reload_after_binding_violates(self):
        tree = ast.parse(
            "_PLANE = None\n"
            "def f(x):\n"
            "    plane = _PLANE\n"
            "    if plane is None:\n"
            "        return x\n"
            "    return _PLANE.g(x)\n")
        bad = check_plane_seam(tree, "f", "_PLANE")
        assert bad is not None and "loaded again" in bad[1]

    def test_allocation_on_off_path_violates(self):
        tree = ast.parse(
            "_PLANE = None\n"
            "def f(x):\n"
            "    plane = _PLANE\n"
            "    if plane is None:\n"
            "        return list(x)\n"
            "    return plane.g(x)\n")
        bad = check_plane_seam(tree, "f", "_PLANE")
        assert bad is not None

    def test_escaping_local_violates(self):
        tree = ast.parse(
            "_PLANE = None\n"
            "def f(x):\n"
            "    plane = _PLANE\n"
            "    if plane is not None:\n"
            "        plane.g(x)\n"
            "    return plane\n")
        bad = check_plane_seam(tree, "f", "_PLANE")
        assert bad is not None and "escapes" in bad[1]

    def test_missing_function_is_loud(self):
        tree = ast.parse("_PLANE = None\n")
        bad = check_plane_seam(tree, "gone", "_PLANE")
        assert bad is not None and "not found" in bad[1]

    def test_package_seams_hold_at_head(self):
        # the real manifest against the real package: faults, trace,
        # health/ledger subscription points, and locks itself
        rep = Report()
        ConcurrencyPass()._check_planes(rep)
        assert not list(rep), [f.render() for f in rep]


# ---------------------------------------------------------------------------
# dynamic sanitizer
# ---------------------------------------------------------------------------

def _in_thread(fn):
    out = []

    def run():
        try:
            out.append(fn())
        except BaseException as e:
            out.append(e)
    t = threading.Thread(target=run)
    t.start()
    t.join(10)
    assert out, "worker thread hung"
    return out[0]


class TestDynamicSanitizer:
    def test_two_lock_inversion_names_the_cycle(self):
        with locks.scoped("record") as san:
            a = locks.named_lock("t.a")
            b = locks.named_lock("t.b")
            _in_thread(lambda: _ordered(a, b))
            _in_thread(lambda: _ordered(b, a))
            cyc = san.cycles()
            assert len(cyc) == 1
            assert set(cyc[0]) == {"t.a", "t.b"}
            assert cyc[0][0] == cyc[0][-1]      # closes on itself

    def test_consistent_order_draws_no_cycle(self):
        with locks.scoped("record") as san:
            a = locks.named_lock("t.a")
            b = locks.named_lock("t.b")
            for _ in range(3):
                _in_thread(lambda: _ordered(a, b))
            assert san.cycles() == []
            assert san.report()["edges"][0]["count"] == 3

    def test_strict_raises_before_the_wedge(self):
        with locks.scoped("strict"):
            a = locks.named_lock("t.a")
            b = locks.named_lock("t.b")
            _in_thread(lambda: _ordered(a, b))
            got = _in_thread(lambda: _ordered(b, a))
            assert isinstance(got, locks.LockOrderError)
            assert got.cycle[0] == got.cycle[-1]
            # the lock itself was NOT left held by the failed acquire
            assert b.locked() is False

    def test_strict_self_deadlock_on_nonreentrant(self):
        with locks.scoped("strict"):
            a = locks.named_lock("t.a")

            def f():
                with a:
                    with a:
                        pass
            got = _in_thread(f)
            assert isinstance(got, locks.LockOrderError)

    def test_reentrant_reacquire_is_fine(self):
        with locks.scoped("strict") as san:
            a = locks.named_lock("t.a", reentrant=True)

            def f():
                with a:
                    with a:
                        return "ok"
            assert _in_thread(f) == "ok"
            assert san.cycles() == []

    def test_order_violation_graded_against_documented(self):
        with locks.scoped("record") as san:
            hi = locks.named_lock("executor.shard_build")
            lo = locks.named_lock("executor.mesh", reentrant=True)
            _in_thread(lambda: _ordered(hi, lo))
            v = san.order_violations()
            assert len(v) == 1
            assert v[0]["held"] == "executor.shard_build"
            assert v[0]["acquired"] == "executor.mesh"

    def test_mesh_lock_notes_into_the_registry(self):
        from dpark_tpu.backend.tpu.executor import _MeshLock
        with locks.scoped("record") as san:
            m = _MeshLock()

            def f():
                with m:
                    with m:         # reentrant: depth only
                        pass
            _in_thread(f)
            rep = san.report()
            assert rep["locks"]["executor.mesh"]["count"] == 1
            assert rep["cycles"] == []

    def test_pr3_export_deadlock_shape_is_named(self):
        """PR 3's export-bucket wedge: a stage held the mesh lock and
        entered the export bridge; the serving side held the export
        lock and needed the mesh — the sanitizer must NAME that cycle
        from one clean interleaving, no wedge required."""
        with locks.scoped("record") as san:
            mesh = locks.named_lock("executor.mesh", reentrant=True)
            export = locks.named_lock("executor.export")

            def stage_side():       # run stage -> export bucket
                with mesh:
                    with export:
                        pass

            def serving_side():     # serve export -> device read
                with export:
                    with mesh:
                        pass
            _in_thread(stage_side)
            _in_thread(serving_side)
            cyc = san.cycles()
            assert len(cyc) == 1
            assert set(cyc[0]) == {"executor.mesh", "executor.export"}
            text = locks.render_report(san.report())
            assert "CYCLE" in text and "executor.export" in text

    def test_acquire_release_api_and_trylock(self):
        with locks.scoped("record") as san:
            a = locks.named_lock("t.a")
            b = locks.named_lock("t.b")

            def f():
                assert a.acquire()
                assert b.acquire(blocking=False)
                b.release()
                a.release()
            _in_thread(f)
            assert [e["from"] for e in san.report()["edges"]] == ["t.a"]


def _ordered(first, second):
    with first:
        with second:
            pass
    return "ok"


# ---------------------------------------------------------------------------
# off-mode contract
# ---------------------------------------------------------------------------

class TestOffMode:
    def test_off_mode_is_inert(self):
        with locks.scoped("off"):
            assert locks.sanitizer() is None
            assert locks.mode() == "off"
            a = locks.named_lock("t.a")
            with a:
                pass
            assert locks.cycles() == []
            assert locks.report() == {"mode": "off"}

    def test_off_mode_never_touches_a_previous_registry(self):
        san = locks.Sanitizer()
        with locks.scoped("off"):
            a = locks.named_lock("t.a")
            b = locks.named_lock("t.b")
            _in_thread(lambda: _ordered(a, b))
            _in_thread(lambda: _ordered(b, a))
        assert san.acquisitions == 0 and san.edges == {}

    def test_configure_modes(self):
        with locks.scoped("off"):
            assert locks.configure("record") is not None
            assert locks.mode() == "record"
            assert locks.configure("strict").strict is True
            assert locks.configure("off") is None
            with pytest.raises(ValueError):
                locks.configure("bogus")


# ---------------------------------------------------------------------------
# record-vs-off parity on a real job
# ---------------------------------------------------------------------------

class TestParity:
    def test_record_mode_is_bit_identical_on_a_chaos_cell(self, ctx):
        data = [(chr(97 + i % 7), i) for i in range(200)]

        def run():
            return sorted(ctx.makeRDD(data, 4)
                          .reduceByKey(lambda a, b: a + b)
                          .collect())
        with locks.scoped("off"):
            base = run()
        with locks.scoped("record") as san:
            checked = run()
            assert san.cycles() == []
        assert checked == base

    def test_dlint_locks_clean_at_head(self):
        from dpark_tpu.analysis.__main__ import main
        assert main(["--locks"]) == 0
