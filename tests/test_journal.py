"""Crash-consistent control plane (ISSUE 20): journaled scheduler
recovery.

The suite proves the journal's three contracts:

- WRITE-AHEAD: a completed shuffle-map stage's outputs are recorded
  (fingerprint + writer sid + locations) before the job proceeds, so a
  kill -9 anywhere later cannot lose the fact of its completion.
- REPLAY: a fresh plane (same dir — the restarted-process view) seeds
  completed stages from the journal: the resubmitted job re-registers
  surviving map outputs and re-runs NOTHING for fully-seeded stages,
  with results bit-identical to the first run.
- REFUSAL: torn tail frames are skipped (counted, never poisoning the
  load), duplicate stage records are idempotent (last wins), and a
  journal written by a NEWER schema is refused whole.

The capstone is the kill -9 leg: a subprocess controller dies at the
first reduce fetch (faults kind=kill — os._exit, no atexit), a second
subprocess replays the journal and completes the job bit-identically
with resumed_stages >= 1 and 0 recomputes.
"""

import operator
import os
import subprocess
import sys

import pytest

from dpark_tpu import journal
from dpark_tpu.utils import frame_jsonl, unframe_jsonl


@pytest.fixture(autouse=True)
def _plane_off():
    """Every test starts and ends with the journal plane disarmed."""
    journal.configure(mode="off")
    yield
    journal.configure(mode="off")


def _reduce_job(ctx):
    return sorted(ctx.parallelize([(i % 7, i) for i in range(210)], 4)
                  .reduceByKey(operator.add, 3).collect())


# ---------------------------------------------------------------------------
# the file format: torn tails, duplicates, schema refusal
# ---------------------------------------------------------------------------

def test_truncated_tail_frame_is_skipped(tmp_path):
    """A frame torn mid-write by a crash is skipped at load (counted),
    and every intact frame before it still replays."""
    d = str(tmp_path / "jnl")
    p = journal._Plane(d)
    p.append({"kind": "stage", "stage": "fp-1", "sid": 1, "nparts": 2,
              "nreduce": 3, "locs": [None, None]})
    p.append({"kind": "stage", "stage": "fp-2", "sid": 2, "nparts": 2,
              "nreduce": 3, "locs": [None, None]})
    path = p._path
    os.close(p._fd)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:-7])                   # tear the last frame
    fresh = journal._Plane(d)
    assert fresh.lookup_stage("fp-1") is not None
    assert fresh.lookup_stage("fp-2") is None
    assert fresh.counters["skipped_frames"] == 1
    assert fresh.counters["refused_files"] == 0


def test_duplicate_stage_records_last_wins(tmp_path):
    """A stage resubmitted after a fetch failure re-journals; replay
    must see its FRESH locations, not the superseded ones."""
    d = str(tmp_path / "jnl")
    p = journal._Plane(d)
    p.append({"kind": "stage", "stage": "fp-1", "sid": 1, "nparts": 1,
              "nreduce": 1, "locs": ["file:///old"]})
    p.append({"kind": "stage", "stage": "fp-1", "sid": 5, "nparts": 1,
              "nreduce": 1, "locs": ["file:///new"]})
    fresh = journal._Plane(d)
    rec = fresh.lookup_stage("fp-1")
    assert rec["sid"] == 5 and rec["locs"] == ["file:///new"]
    assert fresh.counters["skipped_frames"] == 0


def test_newer_schema_journal_is_refused_whole(tmp_path):
    """A journal written by a NEWER schema is refused in its entirety
    — never half-interpreted — while same-schema files still load."""
    d = str(tmp_path / "jnl")
    os.makedirs(d)
    with open(os.path.join(d, "j-newer.jnl"), "wb") as f:
        f.write(frame_jsonl({"kind": "meta",
                             "schema": journal.SCHEMA + 1}))
        f.write(frame_jsonl({"kind": "stage", "stage": "fp-future",
                             "sid": 1, "nparts": 1, "nreduce": 1,
                             "locs": ["file:///x"]}))
    p = journal._Plane(d)
    p.append({"kind": "stage", "stage": "fp-now", "sid": 2,
              "nparts": 1, "nreduce": 1, "locs": ["file:///y"]})
    fresh = journal._Plane(d)
    assert fresh.lookup_stage("fp-future") is None
    assert fresh.lookup_stage("fp-now") is not None
    assert fresh.counters["refused_files"] == 1


def test_frame_round_trip_crc_rejects_corruption():
    line = frame_jsonl({"kind": "stage", "stage": "x"})
    recs, skipped = unframe_jsonl(line)
    assert recs == [{"kind": "stage", "stage": "x"}] and skipped == 0
    bad = bytearray(line)
    bad[len(bad) // 2] ^= 0xFF
    recs, skipped = unframe_jsonl(bytes(bad))
    assert recs == [] and skipped == 1


# ---------------------------------------------------------------------------
# fingerprints: restart-stable stage identity
# ---------------------------------------------------------------------------

def test_stage_fingerprint_stable_across_builds(ctx):
    """Two builds of the same DAG (fresh rdd/shuffle ids) fingerprint
    identically; a different partitioner width does not."""
    from dpark_tpu.schedule import Stage

    def stage_of(width):
        r = ctx.parallelize([(1, 2)], 2).reduceByKey(operator.add,
                                                     width)
        dep = r.dependencies[0]
        return Stage(dep.rdd, dep, [])

    a, b, c = stage_of(3), stage_of(3), stage_of(4)
    assert a.shuffle_dep.shuffle_id != b.shuffle_dep.shuffle_id
    assert journal.stage_fingerprint(a) == journal.stage_fingerprint(b)
    assert journal.stage_fingerprint(a) != journal.stage_fingerprint(c)


# ---------------------------------------------------------------------------
# replay: in-process restart simulation
# ---------------------------------------------------------------------------

def test_replay_resumes_completed_stage(ctx, tmp_path):
    """A fresh plane over the same dir (the restarted-process view)
    seeds the completed map stage: the second run resumes it — 0
    recomputes — and the result is bit-identical.  The new process
    mints a NEW shuffle id, so this also exercises the sid alias."""
    jdir = str(tmp_path / "jnl")
    journal.configure(mode="on", journal_dir=jdir)
    first = _reduce_job(ctx)
    assert ctx.scheduler.history[-1].get("resumed_stages") is None

    journal.configure(mode="on", journal_dir=jdir)   # "restart"
    second = _reduce_job(ctx)
    rec = ctx.scheduler.history[-1]
    assert second == first
    assert rec["state"] == "done"
    assert rec.get("resumed_stages") == 1
    assert rec.get("seeded_partitions") == 4
    assert rec.get("recomputes", 0) == 0
    st = journal.stats()
    assert st["journal_replays"] == 1
    assert st["recovered_stages"] == 1
    assert st["seeded_partitions"] == 4


def test_replay_recomputes_lost_outputs_by_lineage(ctx, tmp_path):
    """Map outputs deleted after the crash are holes: replay seeds the
    survivors and lineage recomputes ONLY the missing partitions."""
    jdir = str(tmp_path / "jnl")
    journal.configure(mode="on", journal_dir=jdir)
    first = _reduce_job(ctx)
    # find the journaled stage record and destroy map 0's bucket dir
    plane = journal._PLANE
    plane._ensure_loaded()
    (rec,) = plane._stages.values()
    root = rec["locs"][0][len("file://"):]
    import shutil
    shutil.rmtree(os.path.join(root, "shuffle", str(rec["sid"]), "0"))

    journal.configure(mode="on", journal_dir=jdir)
    second = _reduce_job(ctx)
    jrec = ctx.scheduler.history[-1]
    assert second == first
    assert jrec["state"] == "done"
    # 3 of 4 maps seeded; the stage was not FULLY resumed
    assert jrec.get("seeded_partitions") == 3
    assert jrec.get("resumed_stages", 0) == 0
    assert journal.stats()["recovered_stages"] == 0


def test_journal_off_is_bit_identical_and_unsampled(ctx, tmp_path):
    """The plane contract: off means no journal dir is touched and the
    result matches the on-mode run exactly."""
    jdir = str(tmp_path / "jnl")
    journal.configure(mode="on", journal_dir=jdir)
    on = _reduce_job(ctx)
    journal.configure(mode="off")
    assert journal.stats() is None
    off = _reduce_job(ctx)
    assert on == off
    assert ctx.scheduler.history[-1].get("resumed_stages") is None


def test_drain_flushes_journal(tmp_path):
    """The graceful-degradation endpoint: drain stops admission, waits
    out in-flight jobs, and flushes the journal before exit."""
    from dpark_tpu import service
    journal.configure(mode="on",
                      journal_dir=str(tmp_path / "jnl"))
    srv = service.JobServer(master="local", slots=1)
    srv.start()
    try:
        summary = srv.drain(timeout=5.0)
        assert summary["drained"] and summary["journal_flushed"]
        with pytest.raises(RuntimeError, match="draining"):
            next(iter(srv.submit(None, None)))
        assert journal.stats()["flushes"] >= 1
        srv.undrain()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the capstone: kill -9 mid-job, restart, bit-identical completion
# ---------------------------------------------------------------------------

_CHILD = r"""
import operator, sys
from dpark_tpu import DparkContext
c = DparkContext("local")
res = sorted(c.parallelize([(i %% 7, i) for i in range(210)], 4)
             .reduceByKey(operator.add, 3).collect())
rec = c.scheduler.history[-1]
print("CHILD_RESULT %%d %%d"
      %% (sum(k * 100003 + v for k, v in res) %% (1 << 61),
         rec.get("resumed_stages") or 0))
"""


def _run_child(env, expect_kill=False):
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD % ()], env=env,
        capture_output=True, text=True, timeout=120)
    if expect_kill:
        return proc
    assert proc.returncode == 0, proc.stderr
    for line in proc.stdout.splitlines():
        if line.startswith("CHILD_RESULT "):
            _, checksum, resumed = line.split()
            return int(checksum), int(resumed)
    raise AssertionError("no CHILD_RESULT line:\n%s\n%s"
                         % (proc.stdout, proc.stderr))


def test_kill9_mid_job_restart_resumes(tmp_path):
    """kill -9 (faults kind=kill: os._exit, no atexit, no flush) at
    the first reduce fetch — after the map stage journaled — then a
    restarted controller completes the SAME job bit-identically,
    resuming the completed stage from the journal."""
    jdir = str(tmp_path / "jnl")
    workroot = str(tmp_path / "work")
    base = dict(os.environ,
                JAX_PLATFORMS="cpu",
                DPARK_JOURNAL="on",
                DPARK_JOURNAL_DIR=jdir,
                DPARK_WORK_DIR=workroot,
                DPARK_PROGRESS="0")
    base.pop("DPARK_FAULTS", None)

    # the clean expectation, computed here (reduceByKey over ints is
    # deterministic)
    agg = {}
    for i in range(210):
        agg[i % 7] = agg.get(i % 7, 0) + i
    expect = sum(k * 100003 + v
                 for k, v in sorted(agg.items())) % (1 << 61)

    victim = _run_child(
        dict(base, DPARK_FAULTS="shuffle.fetch:nth=1,kind=kill"),
        expect_kill=True)
    assert victim.returncode == 137, (victim.returncode,
                                      victim.stderr)
    assert "CHILD_RESULT" not in victim.stdout
    assert os.listdir(jdir), "victim journaled nothing"

    checksum, resumed = _run_child(base)
    assert checksum == expect
    assert resumed >= 1
