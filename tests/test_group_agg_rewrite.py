"""Graph-build combiner rewrite: groupByKey().mapValue(provable
aggregate) becomes a map-side-combining combineByKey on EVERY master
(rdd._group_agg_rewrite) — exchange volume O(distinct keys), results
identical, error behavior preserved."""

import numpy as np
import pytest


ROWS = [(i % 37, (i * 5) % 13 - 4) for i in range(3000)]


def _groups(rows):
    exp = {}
    for k, v in rows:
        exp.setdefault(k, []).append(v)
    return exp


@pytest.mark.parametrize("f,host", [
    (sum, sum),
    (len, len),
    (min, min),
    (max, max),
    (lambda vs: sum(vs) / len(vs), lambda vs: sum(vs) / len(vs)),
])
def test_rewrite_matches_group_semantics(ctx, f, host):
    r = ctx.parallelize(ROWS, 6).groupByKey(4).mapValues(f)
    from dpark_tpu.rdd import MappedValuesRDD, ShuffledRDD
    # the rewrite removed the grouped ShuffledRDD: the graph is a
    # combining shuffle (mean adds one finalize mapValue)
    node = r
    if isinstance(node, MappedValuesRDD):
        node = node.prev
    assert isinstance(node, ShuffledRDD)
    from dpark_tpu.rdd import _mk_list
    assert node.aggregator.create_combiner is not _mk_list
    got = dict(r.collect())
    exp = {k: host(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp


@pytest.mark.mesh
def test_rewrite_cuts_exchange_rows():
    """On the tpu master the rewritten shuffle ships pre-combined rows:
    far fewer valid rows offered for exchange than the no-combine
    grouping ships."""
    from dpark_tpu import DparkContext, conf

    def run(enabled):
        old = conf.GROUP_AGG_REWRITE
        conf.GROUP_AGG_REWRITE = enabled
        c = DparkContext("tpu")
        c.start()
        try:
            got = dict(c.parallelize(ROWS, 8).groupByKey(8)
                       .mapValues(sum).collect())
            rows = c.scheduler.executor.exchange_real_rows
        finally:
            c.stop()
            conf.GROUP_AGG_REWRITE = old
        return got, rows

    got_on, rows_on = run(True)
    got_off, rows_off = run(False)
    assert got_on == got_off
    # 3000 rows over 37 keys on 8 devices: combined rows <= 37*8 per
    # exchange vs 3000 uncombined
    assert rows_on < rows_off / 3, (rows_on, rows_off)


def test_rewrite_preserves_error_behavior(ctx):
    """sum over string values raises on the host path; the rewrite's
    0 + v must raise too, not silently concatenate."""
    rows = [("k", "a"), ("k", "b")]
    r = ctx.parallelize(rows, 2).groupByKey(2).mapValues(sum)
    with pytest.raises(Exception):
        r.collect()


def test_rewrite_skips_pinned_groups(ctx):
    """cache()/checkpoint-marked grouped RDDs keep the real grouping
    (the rewrite would bypass the materialization the user asked for);
    min/max over strings still work through the rewrite (comparison
    semantics are pairwise-equal)."""
    from dpark_tpu.rdd import MappedValuesRDD
    g = ctx.parallelize(ROWS, 4).groupByKey(4).cache()
    r = g.mapValues(sum)
    assert isinstance(r, MappedValuesRDD)    # not rewritten
    got = dict(r.collect())
    assert got == {k: sum(vs) for k, vs in _groups(ROWS).items()}

    srows = [(i % 5, "s%02d" % (i % 23)) for i in range(200)]
    got = dict(ctx.parallelize(srows, 4).groupByKey(4)
               .mapValues(min).collect())
    assert got == {k: min(vs) for k, vs in _groups(srows).items()}


def test_rewrite_mean_float32_width(ctx):
    """mean keeps the host's width semantics through the rewrite."""
    rows = [(i % 7, np.float32(i % 5)) for i in range(280)]
    got = dict(ctx.parallelize(rows, 4).groupByKey(4)
               .mapValues(lambda vs: sum(vs) / len(vs)).collect())
    exp = {}
    for k, vs in _groups(rows).items():
        acc = 0
        for v in vs:
            acc = acc + v
        exp[k] = acc / len(vs)
    assert set(got) == set(exp)
    for k in got:
        assert np.float32(got[k]) == np.float32(exp[k])


def test_partitionby_mapvalue_not_rewritten(ctx):
    """partitionBy keeps flat (k, v) rows — mapValue(sum) there applies
    to each VALUE and must not be treated as a group aggregate."""
    rows = [(i % 5, [i, i + 1]) for i in range(50)]
    got = dict(ctx.parallelize(rows, 4).partitionBy(4)
               .mapValue(sum).collect())
    # sum of each [i, i+1] list value
    assert got
    for k, v in got.items():
        assert isinstance(v, int)


def test_np_aggregates_not_rewritten(ctx):
    """np.sum/np.mean flatten a LIST of array values; the pairwise
    rewrite would compute elementwise instead — np twins must keep the
    real grouping (review finding)."""
    from dpark_tpu.rdd import MappedValuesRDD
    rows = [(i % 3, np.asarray([i, i + 1.0])) for i in range(30)]
    r = ctx.parallelize(rows, 4).groupByKey(4).mapValues(np.mean)
    assert isinstance(r, MappedValuesRDD)    # not rewritten
    got = dict(r.collect())
    exp = {k: float(np.mean(vs)) for k, vs in _groups(rows).items()}
    for k in exp:
        assert abs(got[k] - exp[k]) < 1e-9


def test_builtin_sum_over_arrays_still_rewrites(ctx):
    """builtin sum over array values IS pairwise-equal (chained +):
    the rewrite applies and matches."""
    rows = [(i % 3, np.asarray([i, i * 2])) for i in range(30)]
    got = dict(ctx.parallelize(rows, 4).groupByKey(4)
               .mapValues(sum).collect())
    for k, vs in _groups(rows).items():
        assert np.array_equal(got[k], sum(vs))


def test_materialized_group_not_rewritten(ctx):
    """Once a grouped RDD's shuffle outputs exist, later aggregates
    reuse them instead of re-scanning the parent (review finding)."""
    from dpark_tpu.rdd import MappedValuesRDD
    g = ctx.parallelize(ROWS, 4).groupByKey(4)
    assert g.count() == len(_groups(ROWS))     # materializes g's dep
    r = g.mapValues(sum)
    assert isinstance(r, MappedValuesRDD)          # reuse, no rewrite
    got = dict(r.collect())
    assert got == {k: sum(vs) for k, vs in _groups(ROWS).items()}
