"""Parity fuzzer (SURVEY.md 7.1 step 11): random RDD programs must
produce identical results on the tpu master and the local master — the
local master is the golden model, whatever path (array or object) the
tpu master picks per stage."""

import operator
import random

import pytest


OPS = ["map_affine", "filter_mod", "map_swap", "reduce_sum", "reduce_min",
       "reduce_max", "group", "group_agg", "sort", "distinct_keys",
       "count_tail", "union_extra", "host_partitions", "join_dim"]


def build_program(rng, depth=4):
    """A random pipeline as a list of (op, params); applied identically
    to both contexts."""
    prog = []
    shuffled = False
    for _ in range(depth):
        op = rng.choice(OPS)
        if op == "map_affine":
            prog.append(("map_affine", rng.randint(1, 5),
                         rng.randint(-10, 10)))
        elif op == "filter_mod":
            prog.append(("filter_mod", rng.randint(2, 5),
                         rng.randint(0, 1)))
        elif op == "map_swap":
            prog.append(("map_swap", rng.randint(1, 7)))
        elif op == "union_extra":
            prog.append(("union_extra", rng.randint(0, 2 ** 30)))
        elif op == "host_partitions":
            # an untraceable op: forces THIS stage onto the object path,
            # exercising the HBM export bridge mid-pipeline
            prog.append(("host_partitions",))
        elif op == "join_dim":
            # inner join with a small dim table, values flattened back
            # to ints — exercises the device join source + downstream
            prog.append(("join_dim", rng.randint(2, 40),
                         rng.choice([2, 4, 8])))
        elif op == "group_agg":
            # groupByKey().mapValues(provable aggregate): rides the
            # device segment-scatter path ("mean" stays out of the fuzz
            # set — float sums reassociate; it has deterministic unit
            # tests in test_seg_groups.py)
            if shuffled and rng.random() < 0.5:
                continue
            prog.append(("group_agg", rng.choice([2, 4, 8]),
                         rng.choice(["sum", "len", "min", "max"])))
            shuffled = True
        elif op in ("reduce_sum", "reduce_min", "reduce_max", "group",
                    "sort", "distinct_keys"):
            if shuffled and rng.random() < 0.5:
                continue                 # limit chained shuffles a bit
            prog.append((op, rng.choice([2, 4, 8])))
            shuffled = True
    if not prog:
        prog = [("map_affine", 2, 1)]
    return prog


def apply_program(ctx, data, prog):
    r = ctx.parallelize(data, 8)
    for step in prog:
        op = step[0]
        if op == "map_affine":
            _, a, b = step
            r = r.map(lambda kv, a=a, b=b: (kv[0], kv[1] * a + b))
        elif op == "filter_mod":
            _, m, want = step
            r = r.filter(lambda kv, m=m, w=want: kv[0] % m == w)
        elif op == "map_swap":
            _, m = step
            r = r.map(lambda kv, m=m: (kv[1] % m, kv[0]))
        elif op == "reduce_sum":
            r = r.reduceByKey(operator.add, step[1])
        elif op == "reduce_min":
            r = r.reduceByKey(lambda a, b: a if a < b else b, step[1])
        elif op == "reduce_max":
            r = r.reduceByKey(lambda a, b: a if a > b else b, step[1])
        elif op == "group":
            r = r.groupByKey(step[1]) \
                 .mapValue(lambda vs: sum(vs) if isinstance(vs, list)
                           else vs)
        elif op == "group_agg":
            f = {"sum": sum, "len": len, "min": min, "max": max}[step[2]]
            r = r.groupByKey(step[1]).mapValues(f)
        elif op == "sort":
            r = r.sortByKey(numSplits=step[1])
        elif op == "distinct_keys":
            r = r.map(lambda kv: (kv[0], 0)).reduceByKey(
                lambda a, b: 0, step[1])
        elif op == "union_extra":
            seed2 = step[1]
            extra = [((seed2 + i) % 97, i % 13) for i in range(64)]
            r = r.union(ctx.parallelize(extra, 8))
        elif op == "host_partitions":
            r = r.mapPartitions(lambda it: list(it))
        elif op == "join_dim":
            _, ksp, nsp = step
            dim = [(i - ksp // 2, i * 3 + 1) for i in range(ksp)]
            r = (r.map(lambda kv, m=ksp: (kv[0] % m - m // 2, kv[1]))
                 .join(ctx.parallelize(dim, 8), nsp)
                 .map(lambda kv: (kv[0], kv[1][0] + kv[1][1])))
    return r


def canonical(rows):
    return sorted((int(k), int(v)) for k, v in rows)


@pytest.mark.parametrize("seed", range(20))
def test_random_program_parity(seed):
    from dpark_tpu import DparkContext
    rng = random.Random(seed)
    n = rng.choice([100, 1000, 4096])
    kspace = rng.choice([3, 17, 256, 10_000])
    data = [(rng.randint(-kspace, kspace), rng.randint(-1000, 1000))
            for _ in range(n)]
    prog = build_program(rng)

    tctx = DparkContext("tpu")
    lctx = DparkContext("local")
    try:
        rt = apply_program(tctx, data, prog)
        rl = apply_program(lctx, data, prog)
        got = canonical(rt.collect())
        expect = canonical(rl.collect())
        assert got == expect, "parity violation for program %r" % (prog,)
        # ACTIONS too: count (device counts leaf) and monoid reduce
        # (per-device reduction) must agree with the local master
        assert rt.count() == rl.count() == len(expect), prog
        if expect:
            va = rt.map(lambda kv: kv[1]).reduce(operator.add)
            vb = rl.map(lambda kv: kv[1]).reduce(operator.add)
            if isinstance(va, float) or isinstance(vb, float):
                # device reduce answers from per-device reductions;
                # float summation order differs from the host fold —
                # compare with a tolerance (ADVICE r4)
                import math
                assert math.isclose(va, vb, rel_tol=1e-9,
                                    abs_tol=1e-9), prog
            else:
                assert va == vb, prog
    finally:
        tctx.stop()
        lctx.stop()


def _text_chain(ctx, path, prog, splitSize):
    r = ctx.textFile(path, splitSize=splitSize)
    kind = prog[0]
    if kind == "canonical":
        r = r.flatMap(lambda line: line.split()).map(lambda w: (w, 1))
    elif kind == "lengths":
        r = r.flatMap(lambda line: [(w[:2], len(w))
                                    for w in line.split()])
    else:                       # int keys
        r = r.map(lambda l, m=prog[1]: (len(l) % m, 1))
    red = prog[-1]
    if red == "sum":
        return r.reduceByKey(lambda a, b: a + b, 4)
    if red == "max":
        return r.reduceByKey(lambda a, b: max(a, b), 4)
    return r.groupByKey(4).mapValue(
        lambda vs: sum(vs) if isinstance(vs, list) else vs)


@pytest.mark.parametrize("seed", range(6))
def test_text_chain_parity(seed, tmp_path):
    """Random text-source chains: host-prologue ingest + encode +
    device shuffle == local object path, across split layouts."""
    from dpark_tpu import DparkContext
    rng = random.Random(1000 + seed)
    words = ["w%d" % i for i in range(rng.choice([5, 40, 300]))]
    p = str(tmp_path / "fuzz.txt")
    with open(p, "w") as f:
        for _ in range(rng.randint(200, 2000)):
            f.write(" ".join(rng.choices(words,
                                         k=rng.randint(1, 9))) + "\n")
    prog = (rng.choice([("canonical",), ("lengths",),
                        ("intkey", rng.randint(2, 9))])
            + (rng.choice(["sum", "max", "group"]),))
    splitSize = rng.choice([1000, 7000, None])

    tctx = DparkContext("tpu")
    lctx = DparkContext("local")
    try:
        got = sorted(_text_chain(tctx, p, prog, splitSize).collect())
        expect = sorted(_text_chain(lctx, p, prog, splitSize).collect())
        assert got == expect, "parity violation for %r" % (prog,)
    finally:
        tctx.stop()
        lctx.stop()
