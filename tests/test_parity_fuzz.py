"""Parity fuzzer (SURVEY.md 7.1 step 11): random RDD programs must
produce identical results on the tpu master and the local master — the
local master is the golden model, whatever path (array or object) the
tpu master picks per stage."""

import operator
import random

import pytest


OPS = ["map_affine", "filter_mod", "map_swap", "reduce_sum", "reduce_min",
       "reduce_max", "group", "group_agg", "sort", "distinct_keys",
       "count_tail", "union_extra", "host_partitions", "join_dim",
       "cartesian_dim", "zip_index", "sample_det", "tuple_key",
       "seg_map"]


def _seg_fns():
    """Traceable per-group functions BEYOND the five provable
    aggregates (the ISSUE 4 SegMapOp shapes) — module-level singletons
    so classification/program caches key stably across contexts.
    Mixed zero-pad (sums) and repeat-pad (order statistics) forms."""
    import jax.numpy as jnp
    return {
        "sumsq": lambda vs: sum(v * v for v in vs),
        "amax": lambda vs: jnp.max(jnp.asarray(vs)),
        "amin": lambda vs: jnp.min(jnp.asarray(vs)),
        "span": lambda vs: jnp.max(jnp.asarray(vs))
        - jnp.min(jnp.asarray(vs)),
        "wsum": lambda vs: 3 * sum(vs) + sum(v * v for v in vs),
    }


_SEG_FN_CACHE = {}


def _seg_fn(kind):
    if not _SEG_FN_CACHE:
        _SEG_FN_CACHE.update(_seg_fns())
    return _SEG_FN_CACHE[kind]


def build_program(rng, depth=4):
    """A random pipeline as a list of (op, params); applied identically
    to both contexts."""
    prog = []
    shuffled = False
    for _ in range(depth):
        op = rng.choice(OPS)
        if op == "map_affine":
            prog.append(("map_affine", rng.randint(1, 5),
                         rng.randint(-10, 10)))
        elif op == "filter_mod":
            prog.append(("filter_mod", rng.randint(2, 5),
                         rng.randint(0, 1)))
        elif op == "map_swap":
            prog.append(("map_swap", rng.randint(1, 7)))
        elif op == "union_extra":
            prog.append(("union_extra", rng.randint(0, 2 ** 30)))
        elif op == "host_partitions":
            # an untraceable op: forces THIS stage onto the object path,
            # exercising the HBM export bridge mid-pipeline
            prog.append(("host_partitions",))
        elif op == "cartesian_dim":
            prog.append(("cartesian_dim", rng.randint(2, 4)))
        elif op == "zip_index":
            # order-sensitive: device shuffles return rows key-sorted
            # while the host object path keeps bucket insertion order —
            # both are valid RDD semantics, so index-dependent ops only
            # fuzz BEFORE the first shuffle
            if not shuffled:
                prog.append(("zip_index",))
        elif op == "sample_det":
            if not shuffled:             # per-row rng: order-sensitive
                prog.append(("sample_det", rng.choice([0.3, 0.6]),
                             rng.randint(1, 10_000)))
        elif op == "join_dim":
            # inner join with a small dim table, values flattened back
            # to ints — exercises the device join source + downstream.
            # A join is a shuffle: row order downstream is unspecified
            prog.append(("join_dim", rng.randint(2, 40),
                         rng.choice([2, 4, 8])))
            shuffled = True
        elif op == "group_agg":
            # groupByKey().mapValues(provable aggregate): rides the
            # device segment-scatter path ("mean" stays out of the fuzz
            # set — float sums reassociate; it has deterministic unit
            # tests in test_seg_groups.py)
            if shuffled and rng.random() < 0.5:
                continue
            prog.append(("group_agg", rng.choice([2, 4, 8]),
                         rng.choice(["sum", "len", "min", "max"])))
            shuffled = True
        elif op == "seg_map":
            # groupByKey().mapValues(traceable non-provable f): the
            # ISSUE 4 SegMapOp shape under random surroundings, over
            # whatever ragged group-size distribution the pipeline
            # produced
            if shuffled and rng.random() < 0.5:
                continue
            prog.append(("seg_map", rng.choice([2, 4, 8]),
                         rng.choice(["sumsq", "amax", "amin", "span",
                                     "wsum"])))
            shuffled = True
        elif op == "tuple_key":
            # composite ((k1, k2), v) keys through a device shuffle
            # (reduce/group/sort), keys flattened back to ints after —
            # the ISSUE 3 tentpole shape under random surroundings
            if shuffled and rng.random() < 0.5:
                continue
            prog.append(("tuple_key", rng.randint(2, 30),
                         rng.randint(2, 7),
                         rng.choice(["sum", "min", "group", "sort"]),
                         rng.choice([2, 4, 8])))
            shuffled = True
        elif op in ("reduce_sum", "reduce_min", "reduce_max", "group",
                    "sort", "distinct_keys"):
            if shuffled and rng.random() < 0.5:
                continue                 # limit chained shuffles a bit
            prog.append((op, rng.choice([2, 4, 8])))
            shuffled = True
    if not prog:
        prog = [("map_affine", 2, 1)]
    return prog


def apply_program(ctx, data, prog):
    r = ctx.parallelize(data, 8)
    for step in prog:
        op = step[0]
        if op == "map_affine":
            _, a, b = step
            r = r.map(lambda kv, a=a, b=b: (kv[0], kv[1] * a + b))
        elif op == "filter_mod":
            _, m, want = step
            r = r.filter(lambda kv, m=m, w=want: kv[0] % m == w)
        elif op == "map_swap":
            _, m = step
            r = r.map(lambda kv, m=m: (kv[1] % m, kv[0]))
        elif op == "reduce_sum":
            r = r.reduceByKey(operator.add, step[1])
        elif op == "reduce_min":
            r = r.reduceByKey(lambda a, b: a if a < b else b, step[1])
        elif op == "reduce_max":
            r = r.reduceByKey(lambda a, b: a if a > b else b, step[1])
        elif op == "group":
            r = r.groupByKey(step[1]) \
                 .mapValue(lambda vs: sum(vs) if isinstance(vs, list)
                           else vs)
        elif op == "group_agg":
            f = {"sum": sum, "len": len, "min": min, "max": max}[step[2]]
            r = r.groupByKey(step[1]).mapValues(f)
        elif op == "seg_map":
            r = r.groupByKey(step[1]).mapValues(_seg_fn(step[2]))
        elif op == "sort":
            r = r.sortByKey(numSplits=step[1])
        elif op == "distinct_keys":
            r = r.map(lambda kv: (kv[0], 0)).reduceByKey(
                lambda a, b: 0, step[1])
        elif op == "union_extra":
            seed2 = step[1]
            extra = [((seed2 + i) % 97, i % 13) for i in range(64)]
            r = r.union(ctx.parallelize(extra, 8))
        elif op == "host_partitions":
            r = r.mapPartitions(lambda it: list(it))
        elif op == "cartesian_dim":
            _, m = step
            dim = [(i, i + 1) for i in range(m)]
            r = (r.cartesian(ctx.parallelize(dim, 2))
                 .map(lambda ab: (ab[0][0] + ab[1][0],
                                  ab[0][1] + ab[1][1])))
        elif op == "zip_index":
            # zipWithIndex depends on partition layout, which the two
            # masters share for identical programs; fold the index in
            r = r.zipWithIndex().map(
                lambda kvi: (kvi[0][0], kvi[0][1] + kvi[1] % 13))
        elif op == "sample_det":
            _, frac, sseed = step
            r = r.sample(False, frac, sseed)
        elif op == "join_dim":
            _, ksp, nsp = step
            dim = [(i - ksp // 2, i * 3 + 1) for i in range(ksp)]
            r = (r.map(lambda kv, m=ksp: (kv[0] % m - m // 2, kv[1]))
                 .join(ctx.parallelize(dim, 8), nsp)
                 .map(lambda kv: (kv[0], kv[1][0] + kv[1][1])))
        elif op == "tuple_key":
            _, m, p, red, nsp = step
            r = r.map(lambda kv, m=m, p=p:
                      ((kv[0] % m - m // 2, kv[1] % p), kv[1]))
            if red == "sum":
                r = r.reduceByKey(operator.add, nsp)
            elif red == "min":
                r = r.reduceByKey(lambda a, b: a if a < b else b, nsp)
            elif red == "group":
                r = r.groupByKey(nsp).mapValues(len)
            else:
                r = r.sortByKey(numSplits=nsp)
            # flatten the tuple key back to a collision-free int so any
            # downstream op keeps its (int, int) record contract
            # (column 2 is bounded by p <= 7 < 37)
            r = r.map(lambda kv: (kv[0][0] * 37 + kv[0][1], kv[1]))
    return r


def canonical(rows):
    return sorted((int(k), int(v)) for k, v in rows)


@pytest.mark.parametrize("seed", range(20))
@pytest.mark.mesh
def test_random_program_parity(seed):
    from dpark_tpu import DparkContext
    rng = random.Random(seed)
    n = rng.choice([100, 1000, 4096])
    kspace = rng.choice([3, 17, 256, 10_000])
    data = [(rng.randint(-kspace, kspace), rng.randint(-1000, 1000))
            for _ in range(n)]
    prog = build_program(rng)

    tctx = DparkContext("tpu")
    lctx = DparkContext("local")
    try:
        rt = apply_program(tctx, data, prog)
        rl = apply_program(lctx, data, prog)
        got = canonical(rt.collect())
        expect = canonical(rl.collect())
        assert got == expect, "parity violation for program %r" % (prog,)
        # ACTIONS too: count (device counts leaf) and monoid reduce
        # (per-device reduction) must agree with the local master
        assert rt.count() == rl.count() == len(expect), prog
        if expect:
            va = rt.map(lambda kv: kv[1]).reduce(operator.add)
            vb = rl.map(lambda kv: kv[1]).reduce(operator.add)
            if isinstance(va, float) or isinstance(vb, float):
                # device reduce answers from per-device reductions;
                # float summation order differs from the host fold —
                # compare with a tolerance (ADVICE r4)
                import math
                assert math.isclose(va, vb, rel_tol=1e-9,
                                    abs_tol=1e-9), prog
            else:
                assert va == vb, prog
    finally:
        tctx.stop()
        lctx.stop()


def _text_chain(ctx, path, prog, splitSize):
    r = ctx.textFile(path, splitSize=splitSize)
    kind = prog[0]
    if kind == "canonical":
        r = r.flatMap(lambda line: line.split()).map(lambda w: (w, 1))
    elif kind == "lengths":
        r = r.flatMap(lambda line: [(w[:2], len(w))
                                    for w in line.split()])
    else:                       # int keys
        r = r.map(lambda l, m=prog[1]: (len(l) % m, 1))
    red = prog[-1]
    if red == "sum":
        return r.reduceByKey(lambda a, b: a + b, 4)
    if red == "max":
        return r.reduceByKey(lambda a, b: max(a, b), 4)
    return r.groupByKey(4).mapValue(
        lambda vs: sum(vs) if isinstance(vs, list) else vs)


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.mesh
def test_text_chain_parity(seed, tmp_path):
    """Random text-source chains: host-prologue ingest + encode +
    device shuffle == local object path, across split layouts."""
    from dpark_tpu import DparkContext
    rng = random.Random(1000 + seed)
    words = ["w%d" % i for i in range(rng.choice([5, 40, 300]))]
    p = str(tmp_path / "fuzz.txt")
    with open(p, "w") as f:
        for _ in range(rng.randint(200, 2000)):
            f.write(" ".join(rng.choices(words,
                                         k=rng.randint(1, 9))) + "\n")
    prog = (rng.choice([("canonical",), ("lengths",),
                        ("intkey", rng.randint(2, 9))])
            + (rng.choice(["sum", "max", "group"]),))
    splitSize = rng.choice([1000, 7000, None])

    tctx = DparkContext("tpu")
    lctx = DparkContext("local")
    try:
        got = sorted(_text_chain(tctx, p, prog, splitSize).collect())
        expect = sorted(_text_chain(lctx, p, prog, splitSize).collect())
        assert got == expect, "parity violation for %r" % (prog,)
    finally:
        tctx.stop()
        lctx.stop()


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.mesh
def test_forced_ooc_columnar_parity(seed):
    """Tiny forced wave sizes push random columnar programs through the
    streamed OOC shuffle paths — in-core results and streamed results
    must be indistinguishable, and both must match the local master
    (VERDICT r4 #9: forced OOC chunk sizes in the fuzzer)."""
    import numpy as np
    from dpark_tpu import Columns, DparkContext
    from dpark_tpu import conf
    rng = random.Random(500 + seed)
    n = 30_000
    kspace = rng.choice([17, 301, 4096])
    keys = np.asarray([rng.randrange(kspace) for _ in range(n)],
                      np.int64)
    vals = np.asarray([rng.randint(-50, 50) for _ in range(n)],
                      np.int64)
    red = rng.choice(["sum", "max", "group", "sort", "segmap"])
    nsp = rng.choice([4, 8, 16])        # 16 > mesh: spilled-run stream
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 2048       # force multi-wave streaming
    try:
        outs = []
        for master in ("tpu", "local"):
            c = DparkContext(master)
            c.start()
            try:
                r = c.parallelize(Columns(keys, vals), 8)
                if red == "sum":
                    r = r.reduceByKey(operator.add, nsp)
                elif red == "max":
                    r = r.reduceByKey(lambda a, b: max(a, b), nsp)
                elif red == "group":
                    r = r.groupByKey(nsp).mapValues(sum)
                elif red == "segmap":
                    # forced-OOC waves feeding the segmented apply:
                    # the spilled no-combine runs load back as a
                    # device batch (executor._seg_batch_from_runs)
                    r = r.groupByKey(nsp).mapValues(_seg_fn("sumsq"))
                else:
                    r = r.sortByKey(numSplits=nsp)
                got = r.collect()
                if red == "sort":
                    # equal-key value order is unspecified (stable on
                    # the host, exchange-order on device): assert the
                    # key order, compare the multiset
                    ks = [k for k, _ in got]
                    assert ks == sorted(ks), (master, seed)
                outs.append(sorted(got))
                if master == "tpu" and red != "sort":
                    assert c.scheduler.executor.shuffle_store, \
                        "did not ride the device"
            finally:
                c.stop()
        assert outs[0] == outs[1], (seed, red, nsp)
    finally:
        conf.STREAM_CHUNK_ROWS = old


def test_tuple_value_reduce_minmax_parity():
    """Satellite regression (r5 advisor, high): a classified monoid
    (min/max) over MULTI-LEAF values must not ride the per-leaf device
    monoid path — the host merges whole records (tuples compare
    lexicographically) while per-leaf reduction mixes leaves from
    different records.  _epilogue_merge now degrades such plans to the
    raw-combiner exchange; results must match the local golden master
    exactly.  A 2-device mesh keeps the map-side bucketize-combine +
    exchange machinery engaged without needing the full virtual mesh."""
    from dpark_tpu import DparkContext

    rng = random.Random(99)
    data = [(rng.randint(0, 20),
             (rng.randint(0, 1000), rng.randint(0, 1000)))
            for _ in range(4000)]

    tctx = DparkContext("tpu:2")
    lctx = DparkContext("local")
    try:
        for fn in (lambda a, b: max(a, b),
                   lambda a, b: min(a, b)):
            rt = sorted(tctx.parallelize(data, 2)
                        .reduceByKey(fn, 2).collect())
            rl = sorted(lctx.parallelize(data, 2)
                        .reduceByKey(fn, 2).collect())
            assert rt == rl, (rt[:3], rl[:3])
    finally:
        tctx.stop()
        lctx.stop()


def test_tuple_key_parity_small_mesh():
    """Composite (tuple) keys on a 2-device mesh (runs on any box, no
    full-mesh marker): reduce/group/sort/join over ((k1, k2), v)
    records match the local golden model exactly, and the shuffle rode
    the device (ISSUE 3 tentpole, fuzzed deterministic shapes)."""
    from dpark_tpu import DparkContext

    rng = random.Random(42)
    data = [((rng.randint(0, 15), rng.randint(-4, 4)),
             rng.randint(-500, 500)) for _ in range(3000)]
    dim = [((rng.randint(0, 15), rng.randint(-4, 4)),
            rng.randint(0, 99)) for _ in range(400)]

    tctx = DparkContext("tpu:2")
    lctx = DparkContext("local")
    tctx.start()
    try:
        def both(make):
            return (sorted(make(tctx)), sorted(make(lctx)))

        got, exp = both(lambda c: c.parallelize(data, 2)
                        .reduceByKey(operator.add, 2).collect())
        assert got == exp
        assert tctx.scheduler.executor.shuffle_store, \
            "tuple-key reduce did not ride the device"
        got, exp = both(lambda c: [
            (k, sorted(v)) for k, v in
            c.parallelize(data, 2).groupByKey(2).collect()])
        assert got == exp
        st = tctx.parallelize(data, 2).sortByKey(numSplits=2).collect()
        sl = lctx.parallelize(data, 2).sortByKey(numSplits=2).collect()
        assert [k for k, _ in st] == [k for k, _ in sl]
        got, exp = both(lambda c: c.parallelize(data, 2)
                        .join(c.parallelize(dim, 2), 2).collect())
        assert got == exp
    finally:
        tctx.stop()
        lctx.stop()


def test_monoid_multileaf_lint_rule_matches_executor_guard():
    """The monoid-multileaf lint rule is the pre-flight twin of the
    _epilogue_merge guard: the exact plan shape the guard degrades is
    the shape the rule flags."""
    from dpark_tpu import DparkContext
    from dpark_tpu.analysis import lint_plan

    ctx = DparkContext("local")
    try:
        r = ctx.parallelize(
            [(1, (2, 3)), (1, (5, 1)), (2, (7, 8))], 2) \
            .reduceByKey(lambda a, b: max(a, b), 2)
        assert any(f.rule == "monoid-multileaf" for f in lint_plan(r))
    finally:
        ctx.stop()
