"""Storage formats: beansdb codec round-trip, tabular columnar format with
pruning (reference: tests/test_beansdb.py, tests/test_tabular.py style)."""

import io
import os

import pytest


def test_beansdb_codec_roundtrip():
    from dpark_tpu.beansdb import BeansdbWriter, read_records
    buf = io.BytesIO()
    w = BeansdbWriter(buf)
    w.write_record("key1", b"small")
    w.write_record("key2", b"x" * 10000)        # compressed
    w.write_record("unicode-键", "值".encode())
    buf.seek(0)
    recs = list(read_records(buf))
    assert [(k, v) for k, v, *_ in recs] == [
        ("key1", b"small"), ("key2", b"x" * 10000),
        ("unicode-键", "值".encode())]


def test_beansdb_crc_detects_corruption():
    from dpark_tpu.beansdb import BeansdbWriter, read_records
    buf = io.BytesIO()
    BeansdbWriter(buf).write_record("k", b"payload")
    data = bytearray(buf.getvalue())
    data[30] ^= 0xFF                            # flip a byte in the body
    with pytest.raises(IOError):
        list(read_records(io.BytesIO(bytes(data))))
    # check_crc=False tolerates it
    recs = list(read_records(io.BytesIO(bytes(data)), check_crc=False))
    assert len(recs) == 1


def test_beansdb_rdd_roundtrip(ctx, tmp_path):
    pairs = [("k%03d" % i, ("v%d" % i).encode()) for i in range(500)]
    ctx.parallelize(pairs, 3).saveAsBeansdb(str(tmp_path / "db"))
    files = os.listdir(str(tmp_path / "db"))
    assert all(f.endswith(".data") for f in files)
    back = ctx.beansdb(str(tmp_path / "db")).collect()
    assert sorted(back) == sorted(pairs)
    raw = ctx.beansdb(str(tmp_path / "db"), raw=True).first()
    assert raw[1][1] == 1                        # version


def test_tabular_roundtrip(ctx, tmp_path):
    rows = [(i, float(i) * 0.5, "name%d" % (i % 10)) for i in range(1000)]
    ctx.parallelize(rows, 4).saveAsTabular(str(tmp_path / "tab"),
                                           ["id", "score", "name"])
    t = ctx.tabular(str(tmp_path / "tab"))
    got = t.collect()
    assert sorted(got) == sorted(rows)


def test_tabular_column_pruning(ctx, tmp_path):
    rows = [(i, i * 2, "junk%d" % i) for i in range(100)]
    ctx.parallelize(rows, 2).saveAsTabular(str(tmp_path / "tab"),
                                           ["a", "b", "c"])
    t = ctx.tabular(str(tmp_path / "tab"), wanted=["b"])
    got = t.collect()
    assert sorted(v for (v,) in got) == sorted(i * 2 for i in range(100))


def test_tabular_chunk_pruning(ctx, tmp_path):
    from dpark_tpu.tabular import write_tabular, read_chunks
    path = str(tmp_path / "one.tab")
    rows = [(i,) for i in range(10000)]
    write_tabular(path, ["x"], rows, chunk_rows=1000)
    # range hits only one chunk
    chunks = list(read_chunks(path, predicate_ranges={"x": (2500, 2600)}))
    assert len(chunks) == 1
    n, cols = chunks[0]
    assert n == 1000 and cols["x"][0] == 2000
    # no pruning reads all ten
    assert len(list(read_chunks(path))) == 10


def test_tabular_as_table(ctx, tmp_path):
    rows = [(i, i % 5) for i in range(50)]
    ctx.parallelize(rows, 2).saveAsTabular(str(tmp_path / "t"), ["v", "g"])
    t = ctx.tabular(str(tmp_path / "t")).asTable()
    got = t.groupBy("g", "count(*) as n").collect()
    assert sorted((r.g, r.n) for r in got) == [(g, 10) for g in range(5)]
