"""Device top(k): result stages whose tasks are per-partition _TopN
select each device's k best rows ON DEVICE (one argsort, ndev*k rows
egested) when the ordering key classifies; the per-partition heap and
driver merge run unchanged, so results match the local master."""

import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


def _last_kind(tctx):
    rec = tctx.scheduler.history[-1]
    return {s["rdd"]: s.get("kind") for s in rec["stage_info"]}


# 131 generates Z/1009: the value column is a PERMUTATION of 0..1008 —
# injective, so no top-k cutoff ties (tie membership is order-dependent
# on every master and not a parity property)
ROWS = [(i, (i * 131) % 1009) for i in range(1009)]


def test_top_by_value_rides_device(tctx):
    r = tctx.parallelize(ROWS, 8).reduceByKey(lambda a, b: a + b, 8)
    got = r.top(7, key=lambda kv: kv[1])
    kinds = _last_kind(tctx)
    assert "array+top" in kinds.values(), kinds
    exp = sorted(ROWS, key=lambda kv: kv[1], reverse=True)[:7]
    assert got == exp


def test_top_smallest_and_scalar_records(tctx):
    r = tctx.parallelize(ROWS, 8).reduceByKey(lambda a, b: a + b, 8) \
        .map(lambda kv: kv[1])
    got = r.top(5, reverse=True)         # smallest
    assert "array+top" in _last_kind(tctx).values()
    assert got == sorted(v for _, v in ROWS)[:5]
    got = r.top(5)
    assert got == sorted((v for _, v in ROWS), reverse=True)[:5]


def test_top_traced_key_expression(tctx):
    # injective FLOAT key (integer key expressions stay on the host —
    # device i64 wraps where Python ints are exact; ties at the cutoff
    # have order-dependent membership on every master)
    r = tctx.parallelize(ROWS, 8).reduceByKey(lambda a, b: a + b, 8)
    got = r.top(4, key=lambda kv: kv[1] * 2000.0 + kv[0])
    assert "array+top" in _last_kind(tctx).values()
    exp = sorted(ROWS, key=lambda kv: kv[1] * 2000.0 + kv[0],
                 reverse=True)[:4]
    assert sorted(got) == sorted(exp)


def test_top_int_key_expression_falls_back(tctx):
    """An integer key EXPRESSION can exceed i64 on device while the
    host computes exact Python ints — overflow-RISK keys keep the host
    path (the ranged-int interval probe rejects them), and the answer
    stays right."""
    rows = [(1, 2 ** 61), (2, 5), (3, 7)]
    r = tctx.parallelize(rows, 2).reduceByKey(lambda a, b: a + b, 2)
    got = r.top(1, key=lambda kv: kv[1] * 100)
    assert "array+top" not in _last_kind(tctx).values()
    assert got == [(1, 2 ** 61)]


def test_top_ranged_int_key_rides_device(tctx):
    """ISSUE 3 satellite: an int key expression whose interval over the
    batch's actual per-column min/max provably stays inside i64 rides
    the device — `top(k, key=lambda r: r[1]*1000)` over small ints is
    the canonical shape.  The device-computed key then equals the
    host's exact Python int for every record."""
    r = tctx.parallelize(ROWS, 8).reduceByKey(lambda a, b: a + b, 8)
    got = r.top(6, key=lambda kv: kv[1] * 1000)
    assert "array+top" in _last_kind(tctx).values()
    exp = sorted(ROWS, key=lambda kv: kv[1] * 1000, reverse=True)[:6]
    assert got == exp
    # mixed-column affine expression, negative coefficient
    got = r.top(5, key=lambda kv: kv[1] * 2000 - kv[0])
    assert "array+top" in _last_kind(tctx).values()
    exp = sorted(ROWS, key=lambda kv: kv[1] * 2000 - kv[0],
                 reverse=True)[:5]
    assert got == exp
    # product-of-columns shape x*(K - x): interval arithmetic bounds
    # the INTERMEDIATES, so it still qualifies at small ranges and
    # matches the host exactly (a corner check of outputs alone would
    # not be sound for such shapes).  K=3000 keeps the key injective
    # on the 0..1008 value set (f(a)==f(b) needs a+b=3000).
    got = r.top(4, key=lambda kv: kv[1] * (3000 - kv[1]))
    assert "array+top" in _last_kind(tctx).values()
    exp = sorted(ROWS, key=lambda kv: kv[1] * (3000 - kv[1]),
                 reverse=True)[:4]
    assert got == exp


def test_top_extreme_float_keys(tctx):
    """Valid rows whose key equals the float extreme must outrank
    padding (review finding: sentinel collision returned garbage)."""
    rows = [(i, float("-inf")) for i in range(5)] \
        + [(10, 1.0), (11, 2.0)]
    r = tctx.parallelize(rows, 8).reduceByKey(lambda a, b: a + b, 8)
    got = r.top(5, key=lambda kv: kv[1])
    assert "array+top" in _last_kind(tctx).values()
    assert got[:2] == [(11, 2.0), (10, 1.0)]
    assert all(v == float("-inf") and k in range(5)
               for k, v in got[2:])
    got = r.top(4, key=lambda kv: kv[1], reverse=True)
    assert all(v == float("-inf") for _, v in got)


def test_top_untraceable_key_falls_back(tctx):
    rows = ROWS[:1009]                   # value set injective: no ties
    r = tctx.parallelize(rows, 8).reduceByKey(lambda a, b: a + b, 8)
    got = r.top(3, key=lambda kv: str(kv[1]))
    kinds = _last_kind(tctx)
    assert "array+top" not in kinds.values(), kinds
    exp = sorted(rows, key=lambda kv: str(kv[1]), reverse=True)[:3]
    assert got == exp


def test_top_encoded_wordcount(tctx, tmp_path):
    """String-keyed text counts: ordering by the COUNT leaf pre-tops on
    device (ids never order anything); ordering by the word itself
    keeps the host path (ids must not substitute for strings)."""
    p = tmp_path / "t.txt"
    words = []
    for i in range(40):
        words += ["w%02d" % i] * (i + 1)
    p.write_text(" ".join(words) + "\n")
    counts = tctx.textFile(str(p)) \
        .flatMap(lambda line: line.split()) \
        .map(lambda w: (w, 1)) \
        .reduceByKey(lambda a, b: a + b, 8)
    got = counts.top(5, key=lambda kv: kv[1])
    assert "array+top" in _last_kind(tctx).values()
    assert got == [("w%02d" % i, i + 1) for i in range(39, 34, -1)]

    got = counts.top(3)                  # orders by (word, count)
    assert "array+top" not in _last_kind(tctx).values()
    assert got == [("w39", 40), ("w38", 39), ("w37", 38)]


def test_hot_uses_device_top(tctx):
    """rdd.hot() = count + top by count: the canonical heavy-hitters
    action pre-tops on device."""
    data = []
    for i in range(50):
        data += [i] * (i + 1)
    got = tctx.parallelize(data, 8).hot(4)
    assert "array+top" in _last_kind(tctx).values()
    assert got == [(49, 50), (48, 49), (47, 48), (46, 47)]


def test_top_parity_vs_local(tctx):
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    try:
        def prog(c):
            # ROWS[:1009]: the value set is injective — tie membership
            # at the cutoff is order-dependent on every master and not
            # a parity property
            return c.parallelize(ROWS[:1009], 8) \
                .reduceByKey(lambda a, b: a + b, 8) \
                .top(9, key=lambda kv: kv[1])
        assert prog(tctx) == prog(lctx)
    finally:
        lctx.stop()
