"""Multi-controller bulk data plane (ISSUE 12): the 2-process-on-one-
box topology — a PEER CONTROLLER process runs real jobs on a tpu:2
mesh, keeps its shuffle stores HBM-resident, and serves them over its
bucket server; THIS process (a second controller with its own workdir)
fetches the map outputs over the chunked bulk channel and reduces them
through the production fetch/merge machinery, asserting bit-identical
results to the peer's own in-process collect().  Nothing is shared but
the network.

Plus in-process protocol cells: torn/corrupt frame rejection
(dcn.transfer chaos site both sides), bounded retry on the shared
backoff schedule, the per-peer stream window, zero-copy column
assembly into device_put batches, HMAC-tagged streams, and the
JobServer's per-tenant bulk result streams.
"""

import json
import operator
import os
import pickle
import subprocess
import sys
import threading
import time

import pytest

from dpark_tpu import bulkplane, coding, conf, dcn, faults, trace
from dpark_tpu.dependency import Aggregator
from dpark_tpu.shuffle import (DiskSpillMerger, FetchFailed,
                               LocalFileShuffle, read_bucket,
                               read_bucket_any)
from dpark_tpu.utils import atomic_file, compress

# reduce-side merge triples matching the peer's jobs: combined values
# merge with +, no-combine group lists concatenate
_ADD_AGG = Aggregator(lambda v: v, operator.add, operator.add)
_LIST_AGG = Aggregator(lambda v: [v], lambda c, v: c + [v],
                       lambda a, b: a + b)


def _fetch_partition(sid, rid, agg):
    """Exactly what ShuffledRDD.compute does: the production fetcher
    feeding a DiskSpillMerger."""
    from dpark_tpu.env import env
    merger = DiskSpillMerger(agg, shuffle_id=sid, reduce_id=rid)
    env.shuffle_fetcher.fetch(sid, rid, merger.merge)
    return list(merger)


def _register(peer, sid):
    from dpark_tpu.env import env
    env.map_output_tracker.register_outputs(
        sid, list(peer["locs"][str(sid)]))


# ---------------------------------------------------------------------------
# the peer controller process (module-scoped: jax + 3 jobs once)
# ---------------------------------------------------------------------------

_PEER_SCRIPT = r'''
import json, os, pickle, sys, time
workdir, tracker_addr = sys.argv[1], sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
from dpark_tpu.env import env
env.start(is_master=True, environ={"DPARK_WORKDIR": workdir,
                                   "DPARK_BUCKET_SERVER": "1"})
from dpark_tpu.tracker import TrackerClient
from dpark_tpu import DparkContext
t = TrackerClient(tracker_addr)
ctx = DparkContext("tpu:2")
ctx.start()
uri = env.bucket_server.addr
locs = env.map_output_tracker.locs
jobs = {}

def new_sids(before):
    return sorted(s for s in locs if s not in before)

pairs = [(i % 33, i % 7) for i in range(4000)]

before = set(locs)
red = (ctx.parallelize(pairs, 2).map(lambda kv: (kv[0], kv[1] + 1))
       .reduceByKey(lambda a, b: a + b, 2))
ref_red = dict(red.collect())
(sid_red,) = new_sids(before)
jobs["reduce"] = {"sid": sid_red, "nsplits": 2,
                  "ref": pickle.dumps(ref_red, -1).hex()}

before = set(locs)
grp = ctx.parallelize(pairs, 2).groupByKey(2) \
         .mapValue(lambda vs: (len(vs), sum(vs)))
ref_grp = dict(grp.collect())
(sid_grp,) = new_sids(before)
jobs["group"] = {"sid": sid_grp, "nsplits": 2,
                 "ref": pickle.dumps(ref_grp, -1).hex()}

before = set(locs)
left = [(i % 16, i) for i in range(512)]
right = [(j % 16, j * 10) for j in range(64)]
jn = ctx.parallelize(left, 2).join(ctx.parallelize(right, 2), 2)
ref_join = sorted(jn.collect())
sids_join = new_sids(before)
assert len(sids_join) == 2, sids_join
jobs["join"] = {"sids": sids_join, "nsplits": 2,
                "ref": pickle.dumps(ref_join, -1).hex()}

# every map output of every shuffle is served by THIS controller's
# bucket server: peers fetch hbm:// stores through it
pub = {str(s): [uri for _ in ls] for s, ls in locs.items()}
t.set("bulk:jobs", json.dumps(jobs))
t.set("bulk:locs", json.dumps(pub))
t.set("bulk:ready", "1")
print("PEER_READY", flush=True)
deadline = time.time() + 600
while time.time() < deadline and not t.get("bulk:done"):
    time.sleep(0.1)
ctx.stop()
print("PEER_EXIT", flush=True)
'''


@pytest.fixture(scope="module")
def peer(tmp_path_factory):
    """Spawn the serving controller; yields {"jobs", "locs", "proc"}.
    The peer runs with DPARK_SHUFFLE_CODE=rs(4,2) so its export bridge
    can answer per-shard frame requests (the coded chaos cell); its
    OWN jobs are unaffected (the device all_to_all never carries
    parity)."""
    from dpark_tpu.tracker import TrackerServer, TrackerClient
    srv = TrackerServer()
    srv.start()
    tmp = tmp_path_factory.mktemp("bulk-peer")
    script = str(tmp / "peer.py")
    with open(script, "w") as f:
        f.write(_PEER_SCRIPT)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = repo_root + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    child_env["DPARK_SHUFFLE_CODE"] = "rs(4,2)"
    child_env.pop("DPARK_FAULTS", None)
    child_env.pop("XLA_FLAGS", None)
    wd = str(tmp / "wd-peer")
    os.makedirs(wd, exist_ok=True)
    proc = subprocess.Popen(
        [sys.executable, script, wd, srv.addr],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=child_env)
    cli = TrackerClient(srv.addr)
    try:
        deadline = time.time() + 300
        while time.time() < deadline and not cli.get("bulk:ready"):
            if proc.poll() is not None:
                out, _ = proc.communicate()
                raise RuntimeError("peer died during setup:\n%s" % out)
            time.sleep(0.1)
        assert cli.get("bulk:ready"), "peer never became ready"
        jobs = json.loads(cli.get("bulk:jobs"))
        locs = json.loads(cli.get("bulk:locs"))
        for job in jobs.values():
            job["ref"] = pickle.loads(bytes.fromhex(job["ref"]))
        yield {"jobs": jobs, "locs": locs, "proc": proc}
    finally:
        try:
            cli.set("bulk:done", "1")
        except Exception:
            pass
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        cli.close()
        srv.stop()


# ---------------------------------------------------------------------------
# 2-process parity matrix (cross-controller hbm:// over the bulk
# channel, hot path asserted via trace spans)
# ---------------------------------------------------------------------------

def _assert_bulk_only_spans():
    """The acceptance assert: every dcn transfer during the fetches
    rode the bulk channel — the pickled host bridge (a `dcn.transfer`
    span with a bucket kind) never ran."""
    spans = trace.snapshot()
    bulk = [r for r in spans if r["name"] == "dcn.bulk.fetch"]
    bridge = [r for r in spans
              if r["name"] == "dcn.transfer"
              and (r.get("args") or {}).get("kind")
              in ("bucket", "bucket_shard")]
    assert bulk, "no dcn.bulk.fetch spans recorded"
    assert not bridge, "pickled host bridge used: %r" % bridge


def test_two_controller_reduce_parity(peer):
    """reduceByKey: the peer's HBM-resident map outputs, fetched over
    the bulk channel and merged by the production reduce machinery
    INSIDE A REAL LOCAL JOB, are bit-identical to the peer's own
    collect() — with zero resubmits/recomputes, and the stage record
    carrying the remote-fetch byte count."""
    from dpark_tpu import DparkContext
    job = peer["jobs"]["reduce"]
    sid, nsplits = job["sid"], job["nsplits"]
    _register(peer, sid)
    trace.configure("ring")
    rx0 = bulkplane.total_received_bytes()
    try:
        ctx = DparkContext("local")

        def fetch_part(rid):
            return _fetch_partition(sid, rid, _ADD_AGG)

        parts = ctx.parallelize(list(range(nsplits)), nsplits) \
                   .map(fetch_part).collect()
        got = dict(kv for part in parts for kv in part)
        assert got == job["ref"]
        rec = ctx.scheduler.history[-1]
        assert rec.get("resubmits", 0) == 0, rec
        assert rec.get("recomputes", 0) == 0, rec
        # per-stage remote-fetch byte accounting (web UI column)
        assert any(st.get("remote_fetch_bytes", 0) > 0
                   for st in rec.get("stage_info", ())), \
            rec.get("stage_info")
        assert bulkplane.total_received_bytes() > rx0
        _assert_bulk_only_spans()
        ctx.stop()
    finally:
        trace.configure("off")


def test_two_controller_group_parity(peer):
    """groupByKey().mapValues: the peer's no-combine group store,
    fetched over the bulk channel, reproduces the peer's
    mapValue((len, sum)) bit-identically."""
    job = peer["jobs"]["group"]
    sid, nsplits = job["sid"], job["nsplits"]
    _register(peer, sid)
    trace.configure("ring")
    try:
        got = {}
        for rid in range(nsplits):
            for k, vs in _fetch_partition(sid, rid, _LIST_AGG):
                got[k] = (len(vs), sum(vs))
        assert got == job["ref"]
        _assert_bulk_only_spans()
    finally:
        trace.configure("off")


def test_two_controller_join_parity(peer):
    """join: both parent shuffles fetched cross-controller, cogrouped
    with the production CoGroupMerger, pair-expanded — bit-identical
    to the peer's joined collect()."""
    from dpark_tpu.shuffle import CoGroupMerger
    job = peer["jobs"]["join"]
    sid_l, sid_r = job["sids"]
    nsplits = job["nsplits"]
    _register(peer, sid_l)
    _register(peer, sid_r)
    trace.configure("ring")
    try:
        rows = []
        for rid in range(nsplits):
            merger = CoGroupMerger(2)
            for si, sid in enumerate((sid_l, sid_r)):
                merger.extend(si, _fetch_partition(sid, rid,
                                                   _LIST_AGG))
            for k, (ls, rs) in merger:
                for va in ls:
                    for vb in rs:
                        rows.append((k, (va, vb)))
        assert sorted(rows) == job["ref"]
        _assert_bulk_only_spans()
    finally:
        trace.configure("off")


def _coded_round(peer, spec):
    """One seeded chaos round of the cross-controller coded reduce,
    run as a REAL local job: returns (coding stats delta is read by
    the caller) after asserting bit-identical results and zero
    resubmits/recomputes on the job record."""
    from dpark_tpu import DparkContext
    job = peer["jobs"]["reduce"]
    sid, nsplits = job["sid"], job["nsplits"]
    _register(peer, sid)
    faults.configure(spec)
    ctx = DparkContext("local")
    try:
        def fetch_part(rid):
            return _fetch_partition(sid, rid, _ADD_AGG)

        parts = ctx.parallelize(list(range(nsplits)), nsplits) \
                   .map(fetch_part).collect()
        got = dict(kv for part in parts for kv in part)
        assert got == job["ref"]
        rec = ctx.scheduler.history[-1]
        assert rec.get("resubmits", 0) == 0, rec
        assert rec.get("recomputes", 0) == 0, rec
        assert faults.stats()["shuffle.fetch"]["fired"] > 0
    finally:
        ctx.stop()
        faults.configure(None)


def test_two_controller_coded_decode_under_faults(peer, monkeypatch):
    """Coded decode ACROSS CONTROLLERS (the chaos cell): with rs(4,2)
    active, the fastest-k-of-n shard race runs process-to-process over
    bulk shard frames.  Two injection shapes, both completing
    bit-identically with ZERO resubmits/recomputes (decode instead of
    lineage):

    * REPAIR — single-attempt shard fetches with the first two
      attempts failing outright (`times=2` bounds the erasures below
      any bucket's parity count m=2, so a decode failure is
      structurally impossible): parity reconstructs the lost data
      shards, repair > 0.
    * STRAGGLER WIN — injected delays lose the race: parity arrives
      before the slow data shards, straggler_win > 0, no failure
      anywhere.

    The hit->shard mapping rides thread scheduling, so each shape
    retries a few seeded rounds until its counter moves — every round
    still asserts parity and zero lineage recovery."""
    coding.configure("rs(4,2)")
    trace.configure("ring")
    try:
        # repair: permanent loss of the first two shard attempts
        monkeypatch.setattr(conf, "SHUFFLE_SHARD_ATTEMPTS", 1)
        coding.reset_counters()
        for _ in range(8):
            _coded_round(peer, "shuffle.fetch:p=1,seed=0,times=2")
            if coding.stats()["repair"] > 0:
                break
        stats = coding.stats()
        assert stats["repair"] > 0, stats
        assert stats["decode_failures"] == 0, stats

        # straggler win: delays only — no failure mode exists at all
        monkeypatch.setattr(conf, "SHUFFLE_SHARD_ATTEMPTS", 3)
        coding.reset_counters()
        for round_no in range(5):
            _coded_round(
                peer, "shuffle.fetch:p=0.5,seed=%d,kind=delay,ms=250"
                % (11 + round_no))
            if coding.stats()["straggler_win"] > 0:
                break
        stats = coding.stats()
        assert stats["straggler_win"] > 0, stats
        assert stats["decode_failures"] == 0, stats
        _assert_bulk_only_spans()
    finally:
        trace.configure("off")
        faults.configure(None)
        coding.configure(None)
        coding.reset_counters()


def test_two_controller_midstream_loss_recovers(peer):
    """A deterministic mid-stream frame loss on the READING side
    (dcn.transfer nth=1): the first bulk stream dies mid-transfer, the
    bounded-backoff retry re-reads on a fresh connection, and the
    reduce still matches bit-identically."""
    job = peer["jobs"]["reduce"]
    sid, nsplits = job["sid"], job["nsplits"]
    _register(peer, sid)
    before = bulkplane.stats()
    faults.configure("dcn.transfer:nth=1")
    try:
        got = {}
        for rid in range(nsplits):
            got.update(dict(_fetch_partition(sid, rid, _ADD_AGG)))
        assert got == job["ref"]
        after = bulkplane.stats()
        assert after["torn_streams"] > before["torn_streams"]
        assert after["retries"] > before["retries"]
        assert faults.stats()["dcn.transfer"]["fired"] == 1
    finally:
        faults.configure(None)


# ---------------------------------------------------------------------------
# in-process protocol cells
# ---------------------------------------------------------------------------

@pytest.fixture()
def disk_server(tmp_path):
    """A BucketServer over a workdir holding one 2-partition shuffle,
    written by the real map-side path."""
    wd = str(tmp_path / "srv-wd")
    os.makedirs(wd)
    buckets = {0: [("a", 1), ("b", 2)], 1: [("c", 3)]}
    for rid, items in buckets.items():
        path = LocalFileShuffle.get_output_file(51, 0, rid, workdir=wd)
        with atomic_file(path) as f:
            f.write(compress(pickle.dumps(items, -1)))
    srv = dcn.BucketServer(wd, host="127.0.0.1").start()
    yield srv, buckets
    srv.stop()


def test_disk_bucket_rides_bulk_channel(disk_server):
    srv, buckets = disk_server
    before = bulkplane.stats()
    assert read_bucket(srv.addr, 51, 0, 0) == buckets[0]
    assert read_bucket(srv.addr, 51, 0, 1) == buckets[1]
    after = bulkplane.stats()
    assert after["streams"] >= before["streams"] + 2
    assert after["received"].get(srv.addr, 0) \
        > before["received"].get(srv.addr, 0)


def test_bulk_plane_off_uses_plain_protocol(disk_server, monkeypatch):
    srv, buckets = disk_server
    monkeypatch.setattr(conf, "BULK_PLANE", False)
    trace.configure("ring")
    try:
        assert read_bucket(srv.addr, 51, 0, 0) == buckets[0]
        spans = trace.snapshot()
        assert any(r["name"] == "dcn.transfer" for r in spans)
        assert not any(r["name"] == "dcn.bulk.fetch" for r in spans)
    finally:
        trace.configure("off")


def test_corrupt_frame_rejected_then_retried(disk_server):
    """kind=corrupt at the dcn.transfer site flips payload bytes AFTER
    the frame crc was computed over the true bytes (in-flight
    corruption): the receiver rejects the frame, retries on a fresh
    connection, and returns the correct data — never garbage."""
    srv, buckets = disk_server
    before = bulkplane.stats()
    faults.configure("dcn.transfer:nth=1,kind=corrupt")
    try:
        assert read_bucket(srv.addr, 51, 0, 0) == buckets[0]
        after = bulkplane.stats()
        assert after["corrupt_frames"] > before["corrupt_frames"]
        assert after["retries"] > before["retries"]
        assert faults.stats()["dcn.transfer"]["fired"] == 1
    finally:
        faults.configure(None)


def test_peer_death_every_attempt_surfaces_fetchfailed(disk_server):
    """Persistent mid-stream death (every chunk transfer dies): the
    bounded retries exhaust and read_bucket_any translates the
    transport error into FetchFailed — lineage recovery's signal,
    with the real error chained."""
    srv, _ = disk_server
    faults.configure("dcn.transfer:p=1,seed=0")
    try:
        with pytest.raises(FetchFailed) as ei:
            read_bucket_any([srv.addr], 51, 0, 0)
        assert ei.value.__cause__ is not None
    finally:
        faults.configure(None)


def test_midstream_peer_kill_surfaces_fetchfailed(tmp_path):
    """A REAL peer process killed mid-stream: the peer serves a large
    bucket with a per-chunk delay, the fetcher starts reading, the
    peer is SIGKILLed — the torn stream retries against a dead port
    and surfaces as FetchFailed."""
    wd = str(tmp_path / "victim-wd")
    os.makedirs(wd)
    path = LocalFileShuffle.get_output_file(61, 0, 0, workdir=wd)
    big = [(i, os.urandom(64).hex()) for i in range(40000)]
    with atomic_file(path) as f:
        f.write(compress(pickle.dumps(big, -1)))
    script = str(tmp_path / "victim.py")
    with open(script, "w") as f:
        f.write(
            "import sys, time\n"
            "from dpark_tpu.dcn import BucketServer\n"
            "from dpark_tpu import faults\n"
            # slow every chunk so the parent can kill mid-stream
            "faults.configure('dcn.transfer:p=1,seed=0,kind=delay,"
            "ms=400')\n"
            "srv = BucketServer(sys.argv[1], host='127.0.0.1')"
            ".start()\n"
            "print('ADDR %s' % srv.addr, flush=True)\n"
            "time.sleep(600)\n")
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = repo_root + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    child_env["DPARK_BULK_CHUNK_BYTES"] = "65536"
    proc = subprocess.Popen([sys.executable, script, wd],
                            stdout=subprocess.PIPE, text=True,
                            env=child_env)
    try:
        addr = proc.stdout.readline().split()[1]
        got = {}

        def fetch():
            try:
                read_bucket_any([addr], 61, 0, 0)
                got["result"] = "ok"
            except FetchFailed as e:
                got["result"] = e

        t = threading.Thread(target=fetch)
        t.start()
        time.sleep(1.0)          # several 400ms chunk delays in
        proc.kill()              # peer dies mid-stream
        proc.wait()
        t.join(timeout=60)
        assert not t.is_alive(), "fetch hung after peer death"
        assert isinstance(got["result"], FetchFailed), got["result"]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_retry_backoff_reuses_connect_schedule(disk_server,
                                               monkeypatch):
    """The bulk read retry sleeps the SAME exponential-full-jitter
    schedule as the dcn connect retry (dcn.backoff_delays — one
    implementation, two call sites): attempt k sleeps uniform in
    [base*2^k/2, base*2^k]."""
    srv, _ = disk_server
    slept = []
    monkeypatch.setattr(bulkplane.time, "sleep",
                        lambda d: slept.append(d))
    faults.configure("dcn.transfer:p=1,seed=0")
    try:
        with pytest.raises(Exception):
            bulkplane.fetch(srv.addr, ("bulk_bucket", 51, 0, 0))
    finally:
        faults.configure(None)
    attempts = conf.BULK_READ_ATTEMPTS
    assert len(slept) == attempts - 1, slept
    base = conf.DCN_CONNECT_BACKOFF
    for k, d in enumerate(slept):
        assert base * (2 ** k) * 0.5 <= d <= base * (2 ** k), (k, d)


def test_per_peer_stream_window(monkeypatch):
    """BULK_STREAMS_PER_PEER=1 serializes concurrent streams against
    one peer: two fetches of a 0.3s-to-serve payload take >= 0.55s
    wall."""
    monkeypatch.setattr(conf, "BULK_STREAMS_PER_PEER", 1)
    bulkplane._windows.clear()

    def serve(req):
        data = b"x" * 128

        def gen():
            time.sleep(0.3)
            yield data

        return dcn.BulkPayload(
            {"kind": "blob", "nchunks": 1, "total_bytes": len(data)},
            gen())

    srv = dcn.FramedServer(serve, host="127.0.0.1").start()
    uri = "tcp://%s:%d" % srv.bind_address
    try:
        t0 = time.time()
        ts = [threading.Thread(
            target=lambda: bulkplane.fetch(uri, ("bulk_win",)))
            for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert time.time() - t0 >= 0.55
    finally:
        srv.stop()
        bulkplane._windows.clear()


def test_cols_assemble_zero_copy_into_device_put(disk_server,
                                                 monkeypatch):
    """The columnar stream assembles as np.frombuffer VIEWS over the
    received buffer (no copy) and goes straight to jax.device_put —
    and the item reconstruction is bit-identical to the pickled-rows
    form."""
    import numpy as np
    from dpark_tpu import shuffle as shuffle_mod
    srv, _ = disk_server
    cols = [np.arange(100, dtype=np.int64),
            (np.arange(100, dtype=np.int64) * 3) % 17]

    def col_exporter(sid, map_id, reduce_id):
        if sid != 77:
            raise KeyError(sid)
        return {"no_combine": False}, cols

    def rows_exporter(sid, map_id, reduce_id, shard=None):
        if sid != 77:
            raise KeyError(sid)
        return list(zip(cols[0].tolist(), cols[1].tolist()))

    monkeypatch.setitem(shuffle_mod.HBM_COL_EXPORTERS, "t",
                        col_exporter)
    monkeypatch.setitem(shuffle_mod.HBM_EXPORTERS, "t", rows_exporter)
    meta, view = bulkplane.fetch(srv.addr, ("bulk_bucket", 77, 0, 0))
    assert meta["kind"] == "cols", meta
    got_cols = bulkplane.cols_from_buf(meta, view)
    assert [c.tolist() for c in got_cols] == [c.tolist() for c in cols]
    # zero-copy: the views share the received buffer, no owning copy
    assert all(c.base is not None for c in got_cols)
    dev = bulkplane.device_put_cols(meta, view)
    assert [np.asarray(d).tolist() for d in dev] \
        == [c.tolist() for c in cols]
    # and the item form is bit-identical to what the bridge pickles
    assert read_bucket(srv.addr, 77, 0, 0) == rows_exporter(77, 0, 0)


def test_bulk_stream_hmac_tagged_with_secret(disk_server,
                                             monkeypatch):
    srv, buckets = disk_server
    monkeypatch.setenv("DPARK_DCN_SECRET", "s3cret")
    assert read_bucket(srv.addr, 51, 0, 0) == buckets[0]
    # an in-flight corrupted chunk under the secret fails the chunk
    # MAC — which keeps the crc path's BOUNDED RETRY (a transient flip
    # must not skip straight to lineage recovery on secured clusters)
    before = bulkplane.stats()
    faults.configure("dcn.transfer:nth=1,kind=corrupt")
    try:
        assert read_bucket(srv.addr, 51, 0, 1) == buckets[1]
        after = bulkplane.stats()
        assert after["corrupt_frames"] > before["corrupt_frames"]
        assert after["retries"] > before["retries"]
    finally:
        faults.configure(None)


def test_executor_cols_export_matches_rows(tmp_path):
    """export_bucket_cols is a bit-equal columnar twin of
    export_bucket on a real tpu:2 HBM store, for every (map, reduce)
    bucket."""
    from dpark_tpu import DparkContext
    ctx = DparkContext("tpu:2")
    ctx.start()
    try:
        got = dict(ctx.parallelize([(i % 11, i % 5)
                                    for i in range(2000)], 2)
                   .reduceByKey(lambda a, b: a + b, 2).collect())
        assert len(got) == 11
        ex = ctx.scheduler.executor
        assert ex.shuffle_store, "job did not ride the array path"
        sid = sorted(ex.shuffle_store)[-1]
        nonempty = 0
        for map_id in range(2):
            for rid in range(2):
                rows = ex.export_bucket(sid, map_id, rid)
                meta, cols = ex.export_bucket_cols(sid, map_id, rid)
                items = list(zip(cols[0].tolist(),
                                 cols[1].tolist())) if cols else []
                if meta.get("no_combine"):
                    items = [(k, [v]) for k, v in items]
                assert items == rows, (map_id, rid)
                nonempty += bool(rows)
        assert nonempty, "store exported no data at all"
    finally:
        ctx.stop()


def test_service_bulk_result_streams_per_tenant():
    """Remote tenants' job results multiplex over the bulk channel;
    per-tenant stream bytes land in service_stats()['bulk'], and the
    plain path still serves pre-bulk clients (BULK_PLANE off)."""
    from dpark_tpu import service as svc_mod
    framed = svc_mod.serve("127.0.0.1:0", master="local")
    try:
        host, port = framed.bind_address
        addr = "%s:%d" % (host, port)

        def job(ctx):
            return dict(ctx.parallelize(
                [(i % 3, 1) for i in range(300)], 2)
                .reduceByKey(lambda a, b: a + b, 2).collect())

        expect = {0: 100, 1: 100, 2: 100}
        c1 = svc_mod.ServiceClient(addr, client="tenant-a")
        c2 = svc_mod.ServiceClient(addr, client="tenant-b")
        got = {}
        ts = [threading.Thread(
                  target=lambda: got.update(a=c1.run(job))),
              threading.Thread(
                  target=lambda: got.update(b=c2.run(job)))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert got.get("a") == expect and got.get("b") == expect, got
        st = c1.stats()
        assert st["bulk"].get("tenant-a", 0) > 0, st
        assert st["bulk"].get("tenant-b", 0) > 0, st
        # result streams also land in the bulk plane's per-peer sent
        # counters (/metrics must see ALL bulk traffic)
        assert sum(bulkplane.stats()["sent"].values()) > 0
        # pre-bulk client compatibility: the plain single-frame path
        old = conf.BULK_PLANE
        conf.BULK_PLANE = False
        try:
            assert svc_mod.ServiceClient(
                addr, client="tenant-old").run(job) == expect
        finally:
            conf.BULK_PLANE = old
    finally:
        framed.stop()
        svc_mod.shutdown()


def test_broadcast_chunks_ride_bulk(tmp_path):
    """Broadcast chunk files serve over the bulk channel with the
    same P2P serve accounting the origin-serves assertions rely on."""
    from dpark_tpu.broadcast import Broadcast
    from dpark_tpu.env import env
    env.start_bucket_server()
    b = Broadcast({"payload": list(range(200000))})
    uri = env.bucket_server.addr
    d = os.path.join(env.workdir, "broadcast")
    with open(os.path.join(d, "b%d.0" % b.bid), "rb") as f:
        want = f.read()
    got = bulkplane.fetch_bcast(uri, b.bid, 0)
    assert got == want
    assert env.bucket_server.bcast_serves.get((b.bid, 0), 0) >= 1
    b.clear()


def test_metrics_exports_bulk_counters(disk_server):
    """/metrics carries the per-peer byte counters and the stream
    gauge/counters."""
    from dpark_tpu import DparkContext
    from dpark_tpu.web import render_metrics
    srv, buckets = disk_server
    assert read_bucket(srv.addr, 51, 0, 0) == buckets[0]
    ctx = DparkContext("local")
    try:
        text = render_metrics(ctx.scheduler)
        assert "dpark_bulk_bytes_total" in text
        assert 'direction="received"' in text
        assert "dpark_bulk_streams_active" in text
        assert "dpark_bulk_streams_total" in text
    finally:
        ctx.stop()
