"""Device-side ragged groups (VERDICT r4 #3): groupByKey().mapValues(agg)
chains run all-array as segment reductions — the (k, [v]) group lists
never materialize and no host bridge runs.  Every test asserts parity
with the local master (the golden model, SURVEY.md section 4)."""

import numpy as np
import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture(autouse=True)
def _no_rewrite():
    """These tests exercise the device SegAggOp path, which serves
    group->aggregate chains whenever the graph-build combiner rewrite
    (conf.GROUP_AGG_REWRITE) does not apply — disable the rewrite so
    the op path is what actually runs."""
    from dpark_tpu import conf
    old = conf.GROUP_AGG_REWRITE
    conf.GROUP_AGG_REWRITE = False
    yield
    conf.GROUP_AGG_REWRITE = old


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


def _stage_kinds(tctx):
    rec = tctx.scheduler.history[-1]
    return {s["rdd"]: s.get("kind") for s in rec["stage_info"]}


def _groups(rows):
    exp = {}
    for k, v in rows:
        exp.setdefault(k, []).append(v)
    return exp


ROWS = [(i % 53, (i * 7) % 11 - 3) for i in range(4000)]


@pytest.mark.parametrize("f,host", [
    (sum, sum),
    (len, len),
    (min, min),
    (max, max),
    (lambda vs: sum(vs), sum),
    (lambda vs: len(vs), len),
    (lambda vs: sum(vs) / len(vs), lambda vs: sum(vs) / len(vs)),
])
def test_groupby_aggregate_rides_device(tctx, f, host):
    r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(f)
    got = dict(r.collect())
    exp = {k: host(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("MappedValuesRDD") == "array", kinds


def test_groupby_mean_float32_keeps_width(tctx):
    """mean over np.float32 values stays f32 like the host (np.float32
    sum / int is f32), not a silently-declared f64 (review finding)."""
    rows = [(i % 7, np.float32(i % 5) * np.float32(0.25))
            for i in range(560)]
    r = tctx.parallelize(rows, 8).groupByKey(8) \
        .mapValues(lambda vs: sum(vs) / len(vs))
    got = dict(r.collect())
    exp = {}
    for k, vs in _groups(rows).items():
        acc = np.float32(0)
        for v in vs:
            acc = acc + v
        exp[k] = acc / len(vs)
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"
    assert set(got) == set(exp)
    for k in got:
        assert np.float32(got[k]) == np.float32(exp[k]), (k, got[k],
                                                          exp[k])


def test_groupby_minmax_nan_masked(tctx):
    """Documented NaN caveat: NaN values are absent for device min/max
    — equal to the host whenever NaN is not the group's first-arrived
    element, and deterministic either way."""
    rows = [(i % 4, float(i)) for i in range(40)]
    rows += [(k, float("nan")) for k in range(4)]
    got = dict(tctx.parallelize(rows, 8).groupByKey(8)
               .mapValues(min).collect())
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"
    for k in range(4):
        assert got[k] == float(k)        # the non-NaN min


def test_groupby_aggregate_float_values(tctx):
    rows = [(k, v * 0.5) for k, v in ROWS]
    got = dict(tctx.parallelize(rows, 8).groupByKey(8)
               .mapValues(max).collect())
    exp = {k: max(vs) for k, vs in _groups(rows).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_groupby_aggregate_chain_continues_on_device(tctx):
    """Ops after the aggregate (filter) and a downstream shuffle write
    stay on the array path."""
    r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(sum)
    got = dict(r.filter(lambda kv: kv[0] % 2 == 0)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    exp = {k: sum(vs) for k, vs in _groups(ROWS).items() if k % 2 == 0}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("FilteredRDD") == "array", kinds
    assert kinds.get("ShuffledRDD") == "array", kinds


def test_groupby_aggregate_sort_downstream(tctx):
    """groupByKey -> aggregate -> sortByKey: the aggregate output feeds
    a range shuffle on device."""
    got = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(sum) \
        .sortByKey().collect()
    exp = sorted((k, sum(vs)) for k, vs in _groups(ROWS).items())
    assert got == exp


def test_groupby_aggregate_count_only(tctx):
    """count() over the aggregate answers from device counts (one row
    per key, no egest)."""
    n = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(sum).count()
    assert n == len(_groups(ROWS))
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array+counts"


def test_groupby_aggregate_hint(tctx):
    """A user function equivalent to a monoid but written differently
    opts in via __dpark_segagg__."""
    def total(vs):
        acc = 0
        for v in vs:
            acc += v
        return acc
    total.__dpark_segagg__ = "sum"
    got = dict(tctx.parallelize(ROWS, 8).groupByKey(8)
               .mapValues(total).collect())
    exp = {k: sum(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_groupby_unprovable_aggregate_falls_back(tctx):
    """An aggregate the classifier cannot prove takes the host path and
    still matches."""
    got = dict(tctx.parallelize(ROWS, 8).groupByKey(8)
               .mapValues(lambda vs: sorted(vs)[0]).collect())
    exp = {k: min(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") != "array"


def test_groupby_shadowed_builtin_not_classified():
    """A local `sum` shadowing the builtin must NOT classify."""
    from dpark_tpu.backend.tpu import fuse
    ns = {"sum": lambda vs: 42}
    f = eval("lambda vs: sum(vs)", ns)
    assert fuse.classify_segagg(f) is None
    assert fuse.classify_segagg(sum) == "sum"
    assert fuse.classify_segagg(len) == "count"
    assert fuse.classify_segagg(lambda vs: sum(vs) / len(vs)) == "mean"
    assert fuse.classify_segagg(lambda vs: sorted(vs)) is None


def test_groupby_tuple_values_fall_back(tctx):
    """len over a list of tuple values is host-only (segagg needs
    scalar values) but must still match the local master."""
    rows = [(i % 11, (i, i + 1)) for i in range(300)]
    got = dict(tctx.parallelize(rows, 8).groupByKey(8)
               .mapValues(len).collect())
    exp = {k: len(vs) for k, vs in _groups(rows).items()}
    assert got == exp


def test_groupby_aggregate_parity_vs_local(tctx):
    """Cross-master parity on a mixed program."""
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    try:
        def prog(c):
            return sorted(
                c.parallelize(ROWS, 8).groupByKey(8)
                .mapValues(lambda vs: sum(vs))
                .mapValue(lambda s: s * 3).collect())
        assert prog(tctx) == prog(lctx)
    finally:
        lctx.stop()


def test_groupby_single_key_and_single_rows(tctx):
    """Boundary shapes: one key total; one row per key."""
    one_key = [(7, i) for i in range(100)]
    got = dict(tctx.parallelize(one_key, 8).groupByKey(8)
               .mapValues(sum).collect())
    assert got == {7: sum(range(100))}
    distinct = [(i, i * 2) for i in range(64)]
    got = dict(tctx.parallelize(distinct, 8).groupByKey(8)
               .mapValues(sum).collect())
    assert got == {i: i * 2 for i in range(64)}


# ----------------------------------------------------------------------
# device segmented apply (SegMapOp, ISSUE 4 tentpole): arbitrary
# TRACEABLE per-group functions beyond the five provable aggregates
# ----------------------------------------------------------------------

def _jnp():
    import jax.numpy as jnp
    return jnp


def test_seg_map_traceable_fn_rides_device(tctx):
    """groupByKey().mapValues(f) with a traceable zero-pad-invariant f
    (sum of squares — not one of the five provable aggregates) runs
    with all-array stage kinds and matches the local master."""
    from dpark_tpu import DparkContext
    f = lambda vs: sum(v * v for v in vs)           # noqa: E731
    r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(f)
    got = dict(r.collect())
    exp = {k: sum(v * v for v in vs)
           for k, vs in _groups(ROWS).items()}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("MappedValuesRDD") == "array", kinds
    assert not tctx.scheduler.fallback_reasons()


def test_seg_map_edge_pad_order_statistic(tctx):
    """Repeat-last padding admits order statistics the zero fill would
    corrupt (max - min over negative groups)."""
    jnp = _jnp()
    f = lambda vs: jnp.max(jnp.asarray(vs)) - jnp.min(jnp.asarray(vs))  # noqa: E731,E501
    rows = [(k, -v - 1) for k, v in ROWS]           # all-negative values
    r = tctx.parallelize(rows, 8).groupByKey(8).mapValues(f)
    got = {k: int(v) for k, v in r.collect()}
    exp = {k: max(vs) - min(vs) for k, vs in _groups(rows).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_seg_map_chain_and_shuffle_write(tctx):
    """Ops after the segmented apply (filter) and a downstream shuffle
    write stay on the array path."""
    f = lambda vs: sum(v * v for v in vs)           # noqa: E731
    r = (tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(f)
         .filter(lambda kv: kv[0] % 2 == 0)
         .reduceByKey(lambda a, b: a + b, 8))
    got = dict(r.collect())
    exp = {k: sum(v * v for v in vs)
           for k, vs in _groups(ROWS).items() if k % 2 == 0}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("FilteredRDD") == "array", kinds
    assert kinds.get("ShuffledRDD") == "array", kinds


def test_seg_map_power_law_group_sizes(tctx):
    """Power-law group sizes (one huge hub group + a long tail):
    bucketed padding stays proportional to the histogram, results
    exact."""
    rows = [(i % 97 + 1, (i * 5) % 23 - 11) for i in range(2000)]
    rows += [(0, i % 9) for i in range(1500)]       # hub key
    f = lambda vs: 3 * sum(vs) + sum(v * v for v in vs)   # noqa: E731
    r = tctx.parallelize(rows, 8).groupByKey(8).mapValues(f)
    got = dict(r.collect())
    exp = {k: 3 * sum(vs) + sum(v * v for v in vs)
           for k, vs in _groups(rows).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_seg_map_pytree_output_declines_mixed_neutral(tctx):
    """(max, sumsq) needs repeat-pad for one leaf and zero-pad for the
    other — no single fill is neutral, so the stage correctly stays on
    the host (recorded reason) and parity holds through the export
    bridge."""
    jnp = _jnp()
    f = lambda vs: (jnp.max(jnp.asarray(vs)), sum(v * v for v in vs))  # noqa: E731,E501
    r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(f)
    got = {k: (int(a), int(b)) for k, (a, b) in r.collect()}
    exp = {k: (max(vs), sum(v * v for v in vs))
           for k, vs in _groups(ROWS).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "object"
    reasons = tctx.scheduler.fallback_reasons()
    assert any("padding-invariant" in r_ for r_ in reasons), reasons


def test_seg_map_length_dependent_declines(tctx):
    """A function needing the true group length (mean-like beyond the
    provable form) cannot be padding-invariant: host path + reason."""
    jnp = _jnp()
    f = lambda vs: sum(vs) / jnp.asarray(vs).shape[0]     # noqa: E731
    rows = [(k, float(v)) for k, v in ROWS]
    r = tctx.parallelize(rows, 8).groupByKey(8).mapValues(f)
    got = dict(r.collect())
    exp = {k: sum(vs) / len(vs) for k, vs in _groups(rows).items()}
    for k in exp:
        assert abs(float(got[k]) - exp[k]) < 1e-6, k
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "object"


def test_seg_map_compile_budget_guard(tctx):
    """conf.SEG_MIN_ROWS_PER_TRACE far above the data size degrades to
    the host loop with a 'compile budget' reason — results unchanged."""
    from dpark_tpu import conf as _conf
    f = lambda vs: sum(v * v for v in vs)           # noqa: E731
    old = _conf.SEG_MIN_ROWS_PER_TRACE
    _conf.SEG_MIN_ROWS_PER_TRACE = 10_000_000
    try:
        r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(f)
        got = dict(r.collect())
    finally:
        _conf.SEG_MIN_ROWS_PER_TRACE = old
    exp = {k: sum(v * v for v in vs)
           for k, vs in _groups(ROWS).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "object"
    reasons = tctx.scheduler.fallback_reasons()
    assert any("compile budget" in r_ for r_ in reasons), reasons


def test_seg_map_tuple_keys(tctx):
    """Composite (tuple) keys through the segmented apply: segments
    group on EVERY key column."""
    rows = [((k % 7, k % 3), v) for k, v in ROWS]
    f = lambda vs: sum(v * v for v in vs)           # noqa: E731
    r = tctx.parallelize(rows, 2).groupByKey(2).mapValues(f)
    got = dict(r.collect())
    exp = {k: sum(v * v for v in vs)
           for k, vs in _groups(rows).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_seg_map_float_values_ride_device(tctx):
    """FLOAT grouped values must admit too: the padding check compares
    the host float64 list fold against the device-dtype array fold, so
    its tolerance must absorb float32 rounding (~1e-7) while still
    catching O(1) pad errors (review finding — a 1e-9 bar silently
    declined every accumulating float function)."""
    f = lambda vs: sum(3 * v * v + 2 * v for v in vs)   # noqa: E731
    rows = [(k, v * 0.25) for k, v in ROWS]
    r = tctx.parallelize(rows, 8).groupByKey(8).mapValues(f)
    got = dict(r.collect())
    exp = {k: sum(3 * v * v + 2 * v for v in vs)
           for k, vs in _groups(rows).items()}
    assert set(got) == set(exp)
    for k in exp:
        assert abs(float(got[k]) - exp[k]) <= 1e-3 * max(
            1.0, abs(exp[k])), (k, got[k], exp[k])
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"
    assert not tctx.scheduler.fallback_reasons()
