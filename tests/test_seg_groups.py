"""Device-side ragged groups (VERDICT r4 #3): groupByKey().mapValues(agg)
chains run all-array as segment reductions — the (k, [v]) group lists
never materialize and no host bridge runs.  Every test asserts parity
with the local master (the golden model, SURVEY.md section 4)."""

import numpy as np
import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture(autouse=True)
def _no_rewrite():
    """These tests exercise the device SegAggOp path, which serves
    group->aggregate chains whenever the graph-build combiner rewrite
    (conf.GROUP_AGG_REWRITE) does not apply — disable the rewrite so
    the op path is what actually runs."""
    from dpark_tpu import conf
    old = conf.GROUP_AGG_REWRITE
    conf.GROUP_AGG_REWRITE = False
    yield
    conf.GROUP_AGG_REWRITE = old


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


def _stage_kinds(tctx):
    rec = tctx.scheduler.history[-1]
    return {s["rdd"]: s.get("kind") for s in rec["stage_info"]}


def _groups(rows):
    exp = {}
    for k, v in rows:
        exp.setdefault(k, []).append(v)
    return exp


ROWS = [(i % 53, (i * 7) % 11 - 3) for i in range(4000)]


@pytest.mark.parametrize("f,host", [
    (sum, sum),
    (len, len),
    (min, min),
    (max, max),
    (lambda vs: sum(vs), sum),
    (lambda vs: len(vs), len),
    (lambda vs: sum(vs) / len(vs), lambda vs: sum(vs) / len(vs)),
])
def test_groupby_aggregate_rides_device(tctx, f, host):
    r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(f)
    got = dict(r.collect())
    exp = {k: host(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("MappedValuesRDD") == "array", kinds


def test_groupby_mean_float32_keeps_width(tctx):
    """mean over np.float32 values stays f32 like the host (np.float32
    sum / int is f32), not a silently-declared f64 (review finding)."""
    rows = [(i % 7, np.float32(i % 5) * np.float32(0.25))
            for i in range(560)]
    r = tctx.parallelize(rows, 8).groupByKey(8) \
        .mapValues(lambda vs: sum(vs) / len(vs))
    got = dict(r.collect())
    exp = {}
    for k, vs in _groups(rows).items():
        acc = np.float32(0)
        for v in vs:
            acc = acc + v
        exp[k] = acc / len(vs)
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"
    assert set(got) == set(exp)
    for k in got:
        assert np.float32(got[k]) == np.float32(exp[k]), (k, got[k],
                                                          exp[k])


def test_groupby_minmax_nan_masked(tctx):
    """Documented NaN caveat: NaN values are absent for device min/max
    — equal to the host whenever NaN is not the group's first-arrived
    element, and deterministic either way."""
    rows = [(i % 4, float(i)) for i in range(40)]
    rows += [(k, float("nan")) for k in range(4)]
    got = dict(tctx.parallelize(rows, 8).groupByKey(8)
               .mapValues(min).collect())
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"
    for k in range(4):
        assert got[k] == float(k)        # the non-NaN min


def test_groupby_aggregate_float_values(tctx):
    rows = [(k, v * 0.5) for k, v in ROWS]
    got = dict(tctx.parallelize(rows, 8).groupByKey(8)
               .mapValues(max).collect())
    exp = {k: max(vs) for k, vs in _groups(rows).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_groupby_aggregate_chain_continues_on_device(tctx):
    """Ops after the aggregate (filter) and a downstream shuffle write
    stay on the array path."""
    r = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(sum)
    got = dict(r.filter(lambda kv: kv[0] % 2 == 0)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    exp = {k: sum(vs) for k, vs in _groups(ROWS).items() if k % 2 == 0}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("FilteredRDD") == "array", kinds
    assert kinds.get("ShuffledRDD") == "array", kinds


def test_groupby_aggregate_sort_downstream(tctx):
    """groupByKey -> aggregate -> sortByKey: the aggregate output feeds
    a range shuffle on device."""
    got = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(sum) \
        .sortByKey().collect()
    exp = sorted((k, sum(vs)) for k, vs in _groups(ROWS).items())
    assert got == exp


def test_groupby_aggregate_count_only(tctx):
    """count() over the aggregate answers from device counts (one row
    per key, no egest)."""
    n = tctx.parallelize(ROWS, 8).groupByKey(8).mapValues(sum).count()
    assert n == len(_groups(ROWS))
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array+counts"


def test_groupby_aggregate_hint(tctx):
    """A user function equivalent to a monoid but written differently
    opts in via __dpark_segagg__."""
    def total(vs):
        acc = 0
        for v in vs:
            acc += v
        return acc
    total.__dpark_segagg__ = "sum"
    got = dict(tctx.parallelize(ROWS, 8).groupByKey(8)
               .mapValues(total).collect())
    exp = {k: sum(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") == "array"


def test_groupby_unprovable_aggregate_falls_back(tctx):
    """An aggregate the classifier cannot prove takes the host path and
    still matches."""
    got = dict(tctx.parallelize(ROWS, 8).groupByKey(8)
               .mapValues(lambda vs: sorted(vs)[0]).collect())
    exp = {k: min(vs) for k, vs in _groups(ROWS).items()}
    assert got == exp
    assert _stage_kinds(tctx).get("MappedValuesRDD") != "array"


def test_groupby_shadowed_builtin_not_classified():
    """A local `sum` shadowing the builtin must NOT classify."""
    from dpark_tpu.backend.tpu import fuse
    ns = {"sum": lambda vs: 42}
    f = eval("lambda vs: sum(vs)", ns)
    assert fuse.classify_segagg(f) is None
    assert fuse.classify_segagg(sum) == "sum"
    assert fuse.classify_segagg(len) == "count"
    assert fuse.classify_segagg(lambda vs: sum(vs) / len(vs)) == "mean"
    assert fuse.classify_segagg(lambda vs: sorted(vs)) is None


def test_groupby_tuple_values_fall_back(tctx):
    """len over a list of tuple values is host-only (segagg needs
    scalar values) but must still match the local master."""
    rows = [(i % 11, (i, i + 1)) for i in range(300)]
    got = dict(tctx.parallelize(rows, 8).groupByKey(8)
               .mapValues(len).collect())
    exp = {k: len(vs) for k, vs in _groups(rows).items()}
    assert got == exp


def test_groupby_aggregate_parity_vs_local(tctx):
    """Cross-master parity on a mixed program."""
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    try:
        def prog(c):
            return sorted(
                c.parallelize(ROWS, 8).groupByKey(8)
                .mapValues(lambda vs: sum(vs))
                .mapValue(lambda s: s * 3).collect())
        assert prog(tctx) == prog(lctx)
    finally:
        lctx.stop()


def test_groupby_single_key_and_single_rows(tctx):
    """Boundary shapes: one key total; one row per key."""
    one_key = [(7, i) for i in range(100)]
    got = dict(tctx.parallelize(one_key, 8).groupByKey(8)
               .mapValues(sum).collect())
    assert got == {7: sum(range(100))}
    distinct = [(i, i * 2) for i in range(64)]
    got = dict(tctx.parallelize(distinct, 8).groupByKey(8)
               .mapValues(sum).collect())
    assert got == {i: i * 2 for i in range(64)}
