"""RDD semantics through the local DAG scheduler — the anchor test file
(reference: tests/test_rdd.py, SURVEY.md section 4)."""

import os

import pytest


def test_parallelize_collect(ctx):
    assert ctx.parallelize(range(10), 3).collect() == list(range(10))
    assert ctx.makeRDD([1, 2, 3]).collect() == [1, 2, 3]
    assert ctx.parallelize([], 3).collect() == []


def test_map_filter_flatmap(ctx):
    r = ctx.parallelize(range(10), 4)
    assert r.map(lambda x: x * 2).collect() == [x * 2 for x in range(10)]
    assert r.filter(lambda x: x % 2 == 0).collect() == [0, 2, 4, 6, 8]
    assert r.flatMap(lambda x: [x, -x]).count() == 20


def test_glom_mappartitions(ctx):
    r = ctx.parallelize(range(8), 4)
    assert [len(g) for g in r.glom().collect()] == [2, 2, 2, 2]
    assert r.mapPartitions(lambda it: [sum(it)]).collect() == [1, 5, 9, 13]
    got = r.mapPartitionsWithIndex(lambda i, it: [(i, sum(it))]).collect()
    assert got == [(0, 1), (1, 5), (2, 9), (3, 13)]


def test_reduce_fold_aggregate(ctx):
    r = ctx.parallelize(range(1, 101), 7)
    assert r.reduce(lambda a, b: a + b) == 5050
    assert r.fold(0, lambda a, b: a + b) == 5050
    assert r.aggregate(0, lambda a, x: a + 1, lambda a, b: a + b) == 100
    assert r.sum() == 5050
    assert r.count() == 100


def test_take_first_top(ctx):
    r = ctx.parallelize(range(100), 10)
    assert r.take(5) == [0, 1, 2, 3, 4]
    assert r.take(25) == list(range(25))
    assert r.first() == 0
    assert r.top(3) == [99, 98, 97]
    assert r.top(3, reverse=True) == [0, 1, 2]
    assert r.top(2, key=lambda x: -x) == [0, 1]


def test_reduce_by_key(ctx):
    pairs = [("a", 1), ("b", 2), ("a", 3), ("c", 4), ("b", 5)]
    r = ctx.parallelize(pairs, 3)
    got = dict(r.reduceByKey(lambda a, b: a + b).collect())
    assert got == {"a": 4, "b": 7, "c": 4}


def test_group_by_key(ctx):
    pairs = [("a", 1), ("b", 2), ("a", 3)]
    got = dict(ctx.parallelize(pairs, 2).groupByKey().collect())
    assert sorted(got["a"]) == [1, 3]
    assert got["b"] == [2]


def test_combine_by_key_asymmetric(ctx):
    pairs = [("a", 1), ("a", 2), ("b", 3)]
    got = dict(ctx.parallelize(pairs, 2).combineByKey(
        lambda v: [v], lambda c, v: c + [v], lambda c1, c2: c1 + c2,
        2).collect())
    assert sorted(got["a"]) == [1, 2]


def test_distinct_groupby_keyby(ctx):
    r = ctx.parallelize([1, 2, 2, 3, 3, 3], 3)
    assert sorted(r.distinct().collect()) == [1, 2, 3]
    g = dict(ctx.parallelize(range(10), 3).groupBy(lambda x: x % 2)
             .collect())
    assert sorted(g[0]) == [0, 2, 4, 6, 8]
    kb = ctx.parallelize(["aa", "b"], 2).keyBy(len).collect()
    assert kb == [(2, "aa"), (1, "b")]


def test_union_zip(ctx):
    a = ctx.parallelize([1, 2], 2)
    b = ctx.parallelize([3, 4], 2)
    assert (a + b).collect() == [1, 2, 3, 4]
    assert ctx.parallelize(range(4), 2).zip(
        ctx.parallelize("abcd", 2)).collect() == [
            (0, "a"), (1, "b"), (2, "c"), (3, "d")]


def test_zip_with_index(ctx):
    r = ctx.parallelize("abcdef", 3)
    assert r.zipWithIndex().collect() == [
        ("a", 0), ("b", 1), ("c", 2), ("d", 3), ("e", 4), ("f", 5)]


def test_cartesian(ctx):
    got = ctx.parallelize([1, 2], 2).cartesian(
        ctx.parallelize("ab", 2)).collect()
    assert sorted(got) == [(1, "a"), (1, "b"), (2, "a"), (2, "b")]


def test_merge_split(ctx):
    r = ctx.parallelize(range(10), 5).mergeSplit(2)
    assert len(r.splits) == 3
    assert r.collect() == list(range(10))


def test_sort_by_key(ctx):
    import random
    rng = random.Random(42)
    pairs = [(rng.randint(0, 1000), i) for i in range(500)]
    r = ctx.parallelize(pairs, 5)
    got = r.sortByKey(numSplits=4).collect()
    assert [k for k, _ in got] == sorted(k for k, _ in pairs)
    got_desc = r.sortByKey(ascending=False, numSplits=3).collect()
    assert [k for k, _ in got_desc] == sorted(
        (k for k, _ in pairs), reverse=True)


def test_sort_plain(ctx):
    r = ctx.parallelize([5, 3, 1, 4, 2], 3)
    assert r.sort().collect() == [1, 2, 3, 4, 5]
    assert r.sort(reverse=True).collect() == [5, 4, 3, 2, 1]
    assert r.sort(key=lambda x: -x).collect() == [5, 4, 3, 2, 1]


def test_join_family(ctx):
    a = ctx.parallelize([("a", 1), ("b", 2), ("c", 3)], 2)
    b = ctx.parallelize([("a", "x"), ("a", "y"), ("d", "z")], 2)
    assert sorted(a.join(b).collect()) == [("a", (1, "x")), ("a", (1, "y"))]
    lo = dict(a.leftOuterJoin(b).collect())
    assert lo["b"] == (2, None)
    ro = sorted(a.rightOuterJoin(b).collect())
    assert ("d", (None, "z")) in ro
    oo = dict(a.outerJoin(b).collect())
    assert oo["b"] == (2, None) and oo["d"] == (None, "z")


def test_cogroup_copartitioned_narrow(ctx):
    a = ctx.parallelize([(i, i) for i in range(10)], 2).partitionBy(4)
    b = ctx.parallelize([(i, i * 2) for i in range(10)], 3).partitionBy(4)
    got = dict(a.cogroup(b, numSplits=4).collect())
    assert got[3] == ([3], [6])


def test_partition_by_preserves_duplicates(ctx):
    pairs = [("k", i) for i in range(10)]
    r = ctx.parallelize(pairs, 3).partitionBy(4)
    assert sorted(v for _, v in r.collect()) == list(range(10))


def test_count_by_value_key(ctx):
    r = ctx.parallelize(["a", "b", "a", "c", "a"], 3)
    assert r.countByValue() == {"a": 3, "b": 1, "c": 1}
    p = ctx.parallelize([("x", 1), ("y", 2), ("x", 3)], 2)
    assert p.countByKey() == {"x": 2, "y": 1}


def test_lookup(ctx):
    r = ctx.parallelize([(i, i * i) for i in range(20)], 4).partitionBy(4)
    assert r.lookup(7) == [49]
    r2 = ctx.parallelize([("a", 1), ("a", 2)], 2)
    assert sorted(r2.lookup("a")) == [1, 2]


def test_sample(ctx):
    r = ctx.parallelize(range(1000), 4)
    s = r.sample(False, 0.1, seed=7).collect()
    assert 40 < len(s) < 200
    assert set(s) <= set(range(1000))


def test_accumulator(ctx):
    acc = ctx.accumulator(0)
    ctx.parallelize(range(100), 5).foreach(lambda x: acc.add(x))
    assert acc.value == 4950


def test_broadcast(ctx):
    ctx.start()
    b = ctx.broadcast({"x": 42})
    got = ctx.parallelize(range(3), 3).map(lambda i: b.value["x"] + i)
    assert got.collect() == [42, 43, 44]


def test_cache(ctx):
    calls = ctx.accumulator(0)
    r = ctx.parallelize(range(10), 2).map(
        lambda x: (calls.add(1), x * 2)[1]).cache()
    assert r.collect() == [x * 2 for x in range(10)]
    first = calls.value
    assert r.collect() == [x * 2 for x in range(10)]
    assert calls.value == first          # second pass served from cache


def test_checkpoint(ctx, tmp_path):
    r = ctx.parallelize(range(20), 4).map(lambda x: x + 1)
    r.checkpoint(str(tmp_path / "ckpt"))
    assert r.collect() == list(range(1, 21))
    assert r.dependencies == []          # truncated after first job
    assert r.reduce(lambda a, b: a + b) == 210


def test_checkpoint_is_lazy(ctx, tmp_path):
    """Reference semantics (VERDICT r4 #8): checkpoint() before any
    action runs NO job and computes nothing; the first job
    materializes every split (atomic part files), then lineage
    truncates to a CheckpointRDD; later jobs read the files."""
    import os
    from dpark_tpu.rdd import CheckpointRDD
    calls = []

    def spy(x):
        calls.append(x)
        return x * 2

    r = ctx.parallelize(range(12), 3).map(spy)
    ck = str(tmp_path / "lazyck")
    r.checkpoint(ck)
    assert calls == []                   # no eager job
    assert [f for f in os.listdir(ck)
            if f.startswith("part-")] == []   # nothing materialized
    assert r.dependencies != []          # lineage intact pre-compute

    assert sorted(r.collect()) == sorted(x * 2 for x in range(12))
    assert len(calls) == 12              # computed exactly once
    parts = sorted(f for f in os.listdir(ck) if f.startswith("part-"))
    assert parts == ["part-%05d" % i for i in range(3)]

    # promotion: lineage truncated, reads come from the files
    assert isinstance(r._checkpoint_rdd, CheckpointRDD)
    assert r.dependencies == []
    assert sorted(r.collect()) == sorted(x * 2 for x in range(12))
    assert len(calls) == 12              # no recomputation

    # a surviving directory short-circuits a fresh lineage immediately
    calls2 = []

    def spy2(x):
        calls2.append(x)
        return x * 2

    r2 = ctx.parallelize(range(12), 3).map(spy2)
    r2.checkpoint(ck)
    assert isinstance(r2._checkpoint_rdd, CheckpointRDD)
    assert sorted(r2.collect()) == sorted(x * 2 for x in range(12))
    assert calls2 == []


def test_checkpoint_under_process_master(tmp_path):
    """Lazy checkpoint with FORKED workers: parts are written by the
    workers, the driver promotes on its next splits access (review
    finding: workers must never rebuild stripped splits)."""
    from dpark_tpu import DparkContext
    c = DparkContext("process:2")
    try:
        r = c.parallelize(range(12), 3).map(lambda x: x + 1)
        ck = str(tmp_path / "procck")
        r.checkpoint(ck)
        assert sorted(r.collect()) == list(range(1, 13))
        import os
        assert sorted(f for f in os.listdir(ck)
                      if f.startswith("part-")) \
            == ["part-%05d" % i for i in range(3)]
        _ = r.splits                     # driver-side promotion point
        assert r._checkpoint_rdd is not None
        assert sorted(r.collect()) == list(range(1, 13))
    finally:
        c.stop()


def test_checkpoint_stale_dir_discarded(ctx, tmp_path):
    """A checkpoint dir written for a DIFFERENT split layout must not
    silently supply data (review finding)."""
    import os
    ck = str(tmp_path / "staleck")
    r1 = ctx.parallelize(range(6), 6)
    r1.checkpoint(ck)
    assert sorted(r1.collect()) == list(range(6))
    assert r1._checkpoint_rdd is not None
    # a differently-shaped RDD pointed at the same dir: stale parts
    # are discarded, fresh data computes and re-materializes
    r2 = ctx.parallelize([100, 200, 300], 3)
    r2.checkpoint(ck)
    assert r2._checkpoint_rdd is None    # nothing trusted yet
    assert sorted(r2.collect()) == [100, 200, 300]
    assert sorted(f for f in os.listdir(ck)
                  if f.startswith("part-")) \
        == ["part-%05d" % i for i in range(3)]
    assert r2._checkpoint_rdd is not None
    assert sorted(r2.collect()) == [100, 200, 300]


def test_checkpoint_partial_then_complete(ctx, tmp_path):
    """A job touching ONLY some partitions writes only those parts; a
    later whole-RDD job completes the set and promotes."""
    import os
    r = ctx.parallelize(range(12), 3).map(lambda x: x + 1)
    ck = str(tmp_path / "partck")
    r.checkpoint(ck)
    first = list(ctx.runJob(r, list, partitions=[0]))[0]
    assert first == [1, 2, 3, 4]
    assert sorted(f for f in os.listdir(ck)
                  if f.startswith("part-")) == ["part-00000"]
    assert r._checkpoint_rdd is None     # not complete yet
    assert sorted(r.collect()) == list(range(1, 13))
    assert r._checkpoint_rdd is not None
    assert r.dependencies == []


def test_text_file_roundtrip(ctx, tmp_path):
    lines = ["hello world", "foo bar", "第三行 unicode", ""] * 50
    src = tmp_path / "in.txt"
    src.write_text("\n".join(lines) + "\n", encoding="utf-8")
    r = ctx.textFile(str(src), splitSize=256)
    assert len(r.splits) > 1
    assert r.collect() == lines

    out = tmp_path / "out"
    ctx.parallelize(lines, 3).saveAsTextFile(str(out))
    back = ctx.textFile(str(out)).collect()
    assert sorted(back) == sorted(l for l in lines)


def test_wordcount(ctx, tmp_path):
    text = "the quick brown fox jumps over the lazy dog the fox\n" * 20
    src = tmp_path / "wc.txt"
    src.write_text(text)
    counts = dict(
        ctx.textFile(str(src), splitSize=200)
        .flatMap(lambda line: line.split())
        .map(lambda w: (w, 1))
        .reduceByKey(lambda a, b: a + b)
        .collect())
    assert counts["the"] == 60
    assert counts["fox"] == 40
    assert counts["dog"] == 20


def test_csv_roundtrip(ctx, tmp_path):
    rows = [["a", "1"], ["b", "2"], ["c", "3"]] * 10
    ctx.parallelize(rows, 2).saveAsCSVFile(str(tmp_path / "csv"))
    back = ctx.csvFile(str(tmp_path / "csv")).collect()
    assert sorted(back) == sorted(rows)


def test_binary_roundtrip(ctx, tmp_path):
    recs = [(i,) for i in range(1000)]
    ctx.parallelize(recs, 3).saveAsBinaryFile(str(tmp_path / "bin"), "I")
    files = [os.path.join(str(tmp_path / "bin"), f)
             for f in sorted(os.listdir(str(tmp_path / "bin")))]
    got = []
    for f in files:
        got.extend(ctx.binaryFile(f, "I").collect())
    assert sorted(got) == recs


def test_pickle_table_roundtrip(ctx, tmp_path):
    data = [{"a": i} for i in range(50)]
    ctx.parallelize(data, 4).saveAsTableFile(str(tmp_path / "tbl"))
    assert ctx.tableFile(str(tmp_path / "tbl")).collect() == data


def test_gzip_file(ctx, tmp_path):
    import gzip
    p = tmp_path / "x.gz"
    with gzip.open(p, "wt") as f:
        f.write("l1\nl2\nl3\n")
    assert ctx.textFile(str(p)).collect() == ["l1", "l2", "l3"]


def test_pipe(ctx):
    r = ctx.parallelize(["c", "a", "b"], 1).pipe("sort")
    assert r.collect() == ["a", "b", "c"]


def test_hot(ctx):
    data = ["a"] * 5 + ["b"] * 3 + ["c"] * 1
    got = ctx.parallelize(data, 3).hot(2)
    assert got == [("a", 5), ("b", 3)]


def test_foreach_partition_and_enumerate(ctx):
    acc = ctx.accumulator(0)
    ctx.parallelize(range(10), 5).foreachPartition(
        lambda it: acc.add(sum(it)))
    assert acc.value == 45
    parts = ctx.parallelize(range(4), 2).enumeratePartition().collect()
    assert parts == [(0, 0), (0, 1), (1, 2), (1, 3)]


def test_multi_stage_chain(ctx):
    # two consecutive shuffles share the DAG correctly
    r = (ctx.parallelize([(i % 5, i) for i in range(100)], 8)
         .reduceByKey(lambda a, b: a + b)
         .map(lambda kv: (kv[1] % 3, kv[0]))
         .groupByKey(2))
    got = dict(r.collect())
    assert sum(len(v) for v in got.values()) == 5


def test_empty_rdd_actions(ctx):
    r = ctx.parallelize([], 2)
    assert r.collect() == []
    assert r.count() == 0
    assert r.take(3) == []
    with pytest.raises(ValueError):
        r.first()


def test_error_propagates(ctx):
    r = ctx.parallelize(range(4), 2).map(lambda x: 1 // (x - 2))
    with pytest.raises(RuntimeError):
        r.collect()


def test_parallelize_list_of_arrays_keeps_element_semantics(ctx):
    import numpy as np
    pts = [np.array([1.0, 2.0]), np.array([3.0, 4.0]),
           np.array([5.0, 6.0])]
    got = ctx.parallelize(pts, 2).map(lambda p: float(p.sum())).collect()
    assert got == [3.0, 7.0, 11.0]


def test_profile_flag_collects_stats(ctx):
    from dpark_tpu.env import env
    env.profile = True
    try:
        ctx.parallelize(range(100), 4).map(lambda x: x * 2).count()
        assert ctx.scheduler.profile is not None
        assert "run" in ctx.scheduler.profile.summary(5)
    finally:
        env.profile = False
        ctx.scheduler.profile = None


def test_snapshot_materializes_and_rereads(ctx, tmp_path):
    """snapshot(): disk materialization at first compute, reread on
    later jobs, NO lineage truncation; a second RDD over the same path
    short-circuits recomputation (reference RDD.snapshot [L])."""
    calls = []

    def probe(x):
        calls.append(x)
        return x * 2

    r = ctx.parallelize(list(range(20)), 4).map(probe)
    r.snapshot(str(tmp_path / "snap"))
    assert r.collect() == [x * 2 for x in range(20)]
    ncalls = len(calls)
    assert ncalls == 20
    # second job reads the snapshot files — no recompute
    assert r.collect() == [x * 2 for x in range(20)]
    assert len(calls) == ncalls
    # lineage intact: a vanished snapshot recomputes silently
    import shutil
    shutil.rmtree(str(tmp_path / "snap"))
    (tmp_path / "snap").mkdir()
    assert r.collect() == [x * 2 for x in range(20)]
    assert len(calls) == 2 * ncalls


@pytest.mark.mesh
def test_snapshot_on_tpu_master(tmp_path):
    """The tpu master honors snapshot semantics (object path for the
    snapshotted stage) with identical results."""
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    try:
        r = c.parallelize(list(range(40)), 8).map(lambda x: x + 1)
        r.snapshot(str(tmp_path / "snap2"))
        assert sorted(r.collect()) == list(range(1, 41))
        import os
        assert any(f.startswith("part-")
                   for f in os.listdir(str(tmp_path / "snap2")))
        assert sorted(r.collect()) == list(range(1, 41))
    finally:
        c.stop()


def test_union_does_not_flatten_through_checkpoint(ctx, tmp_path):
    """a.union(b).checkpoint() truncates lineage; a later .union(c)
    must read the checkpointed union, not resurrect its parents
    (r4 review finding)."""
    a = ctx.parallelize([1, 2], 2)
    b = ctx.parallelize([3, 4], 2)
    u = a.union(b)
    u.checkpoint(str(tmp_path / "ck"))
    u.collect()                          # materialize the checkpoint
    w = u.union(ctx.parallelize([5], 1))
    from dpark_tpu.rdd import UnionRDD
    assert isinstance(w.rdds[0], UnionRDD) or len(w.rdds) == 2, \
        [type(r).__name__ for r in w.rdds]
    assert sorted(w.collect()) == [1, 2, 3, 4, 5]


def test_checkpoint_textfile_foreign_splits(ctx, tmp_path):
    """Satellite regression (r5 advisor, high): CheckpointRDD.compute
    must decide by split TYPE, not by a duck-typed .path — a
    textFile-derived lineage promotes lazily while downstream
    DerivedRDDs still hold the parent's TextSplits (which carry a
    .path into the source text file).  The downstream consumer must
    read checkpointed parts both before and after promotion."""
    import os
    from dpark_tpu.rdd import CheckpointRDD

    src = tmp_path / "input.txt"
    with open(src, "w") as f:
        for i in range(40):
            f.write("row %d\n" % i)

    base = ctx.textFile(str(src), numSplits=4).map(lambda l: l.upper())
    nsplits = len(base.splits)           # textFile may round up
    ck = str(tmp_path / "txtck")
    base.checkpoint(ck)

    down = base.map(lambda l: l + "!")
    expect = sorted("ROW %d!" % i for i in range(40))

    # job 1: materializes the checkpoint mid-job; downstream planned
    # against the ORIGINAL TextSplits
    assert sorted(down.collect()) == expect

    # promotion happened on the driver
    assert isinstance(base._checkpoint_rdd, CheckpointRDD)
    parts = sorted(f for f in os.listdir(ck) if f.startswith("part-"))
    assert len(parts) == nsplits

    # job 2: down's cached splits are still TextSplits — compute maps
    # them BY INDEX onto part files (duck-typing read the text file
    # here and died in pickle.load across all retries)
    assert sorted(down.collect()) == expect

    # a foreign CheckpointSplit (different directory) maps by index too
    other = ctx.parallelize(range(8), 2).map(lambda x: -x)
    other.checkpoint(str(tmp_path / "otherck"))
    other.collect()
    foreign = other._checkpoint_rdd.splits[0]
    got = list(base._checkpoint_rdd.compute(foreign))
    assert got == list(base._checkpoint_rdd.compute(
        base._checkpoint_rdd.splits[0]))
