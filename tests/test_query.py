"""Columnar query plane (ISSUE 13): tabular v2 footer stats, the
vectorizing expression compiler's exact admissions, planner rules
(pruning / pushdown / chunk skip / group + join lowering / pricing),
the table-host-fallback lint rule, and the SQL literal-escape
regressions."""

import math
import os

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# tabular v2 footer (satellite 1)
# ---------------------------------------------------------------------------

def _write(tmp_path, rows, fields, name="t.tab", chunk_rows=1000,
           version=2):
    from dpark_tpu.tabular import write_tabular
    p = str(tmp_path / name)
    write_tabular(p, fields, rows, chunk_rows=chunk_rows,
                  version=version)
    return p


def test_tabular_v2_footer_stats(tmp_path):
    from dpark_tpu.tabular import chunk_stats, read_header
    rows = [(i, float(i) if i % 10 else float("nan"),
             None if i % 7 == 0 else "s%d" % i)
            for i in range(2500)]
    p = _write(tmp_path, rows, ["a", "f", "s"], chunk_rows=1000)
    h = read_header(p)
    assert h["version"] == 2
    st = chunk_stats(p)
    assert len(st) == 3 and st[0]["rows"] == 1000
    a0 = st[0]["columns"]["a"]
    assert a0["min"] == 0 and a0["max"] == 999 and a0["nulls"] == 0
    # float NaNs count as nulls and stay out of min/max
    f0 = st[0]["columns"]["f"]
    assert f0["nulls"] == 100
    assert f0["min"] == 1.0
    # object columns count None entries
    s0 = st[0]["columns"]["s"]
    assert s0["nulls"] == sum(1 for i in range(1000) if i % 7 == 0)


def test_tabular_v1_files_still_read(tmp_path):
    from dpark_tpu.tabular import chunk_stats, read_chunks, read_header
    rows = [(i, i * 2) for i in range(500)]
    p = _write(tmp_path, rows, ["a", "b"], chunk_rows=200, version=1)
    h = read_header(p)
    assert h["version"] == 1
    got = []
    for n, cols in read_chunks(p):
        got.extend(zip(cols["a"].tolist(), cols["b"].tolist()))
    assert got == rows
    # v1 numeric headers carry min/max (no null counts)
    st = chunk_stats(p)
    assert st[0]["columns"]["a"]["min"] == 0
    assert "nulls" not in st[0]["columns"]["a"]


def test_tabular_v1_v2_same_rows(tmp_path):
    from dpark_tpu.tabular import read_chunks
    rows = [(i, "w%d" % (i % 3)) for i in range(700)]
    p1 = _write(tmp_path, rows, ["a", "s"], "v1.tab", 300, version=1)
    p2 = _write(tmp_path, rows, ["a", "s"], "v2.tab", 300, version=2)

    def all_rows(p):
        out = []
        for n, cols in read_chunks(p):
            out.extend(zip(cols["a"].tolist(), list(cols["s"])))
        return out
    assert all_rows(p1) == all_rows(p2) == rows


def test_read_chunks_stats_accounting(tmp_path):
    from dpark_tpu.tabular import read_chunks
    rows = [(i, i % 5, i * 3) for i in range(4000)]
    p = _write(tmp_path, rows, ["x", "y", "z"], chunk_rows=1000)
    stats = {}
    chunks = list(read_chunks(p, wanted_fields=["x"],
                              predicate_ranges={"x": (2500, 2600)},
                              stats=stats))
    assert len(chunks) == 1
    assert stats["chunks_total"] == 4
    assert stats["chunks_skipped"] == 3
    assert stats["columns_read"] == {"x"}


# ---------------------------------------------------------------------------
# expression vectorizer: exact admissions
# ---------------------------------------------------------------------------

def _vec(expr, dtypes, ranges=None, boolean=False):
    from dpark_tpu.query.exprs import compile_expr, vectorize
    ce = compile_expr(expr, list(dtypes))
    return vectorize(ce, dtypes, ranges, boolean=boolean)


def test_vectorize_arithmetic_matches_host():
    env = {"a": np.array([3, -7, 0, 12], np.int64),
           "f": np.array([1.5, -2.0, 0.25, 9.0], np.float64)}
    dt = {"a": np.int64, "f": np.float64}
    rg = {"a": (-7, 12)}
    for expr in ("a * 2 + 1", "a % 5", "a // 3", "a / 2",
                 "f * a - 1", "abs(a)", "min(a, 4)", "max(a, f)",
                 "-a + 7", "float(a)"):
        ve, reason = _vec(expr, dt, rg)
        assert ve is not None, (expr, reason)
        got = ve.fn(env)
        code = compile(expr, "<t>", "eval")
        for i in range(4):
            exp = eval(code, {"__builtins__": {
                "abs": abs, "min": min, "max": max, "float": float}},
                {"a": int(env["a"][i]), "f": float(env["f"][i])})
            g = got[i] if np.ndim(got) else got
            assert float(g) == float(exp), (expr, i, g, exp)


def test_vectorize_predicates_and_bool_ops():
    env = {"a": np.array([1, 5, 9], np.int64),
           "s": np.array(["x", "y", "x"], object)}
    dt = {"a": np.int64, "s": object}
    ve, _ = _vec("a > 2 and s == 'x'", dt, {"a": (1, 9)}, boolean=True)
    assert ve.fn(env).tolist() == [False, False, True]
    ve, _ = _vec("not (a > 2) or a == 9", dt, {"a": (1, 9)},
                 boolean=True)
    assert ve.fn(env).tolist() == [True, False, True]
    ve, _ = _vec("2 < a < 9", dt, {"a": (1, 9)}, boolean=True)
    assert ve.fn(env).tolist() == [False, True, False]


def test_vectorize_exact_declines():
    dt = {"a": np.int64, "b": np.int64, "s": object}
    rg = {"a": (0, 2 ** 40), "b": (-5, 5)}
    # int overflow: the host computes exact Python ints
    ve, reason = _vec("a * a", dt, rg)
    assert ve is None and "int64" in reason
    # division by a maybe-zero column: the host raises
    ve, reason = _vec("a / b", dt, rg)
    assert ve is None and "nonzero" in reason
    # and/or outside a predicate returns an operand on the host
    ve, reason = _vec("a and b", dt, rg)
    assert ve is None and "and/or" in reason
    # string arithmetic has no device form
    ve, reason = _vec("s + s", dt, rg)
    assert ve is None
    # unknown int range: no no-wrap proof
    ve, reason = _vec("a + 1", {"a": np.int64}, {})
    assert ve is None and "range" in reason


def test_vectorize_min_nan_semantics_match_python():
    # Python min(a, b) returns a when b is NaN (NaN never compares
    # less); np.minimum would propagate the NaN
    env = {"f": np.array([3.0, float("nan")], np.float64)}
    ve, _ = _vec("min(f, 5.0)", {"f": np.float64})
    got = ve.fn(env)
    assert got[0] == 3.0
    assert math.isnan(got[1]) == math.isnan(min(float("nan"), 5.0))
    ve, _ = _vec("max(f, 5.0)", {"f": np.float64})
    assert ve.fn(env)[0] == 5.0


# ---------------------------------------------------------------------------
# planner rules
# ---------------------------------------------------------------------------

@pytest.fixture()
def tab(ctx, tmp_path):
    rows = [(i % 97, i % 50, i % 7, (i % 13) * 0.5,
             "s%d" % (i % 5)) for i in range(20000)]
    path = str(tmp_path / "tab")
    os.makedirs(path)
    _write(tmp_path / "tab", rows, ["k", "a", "b", "f", "s"],
           "part-00000.tab", chunk_rows=2000)
    return ctx.tabular(path).asTable("t"), rows


def _decisions(t, rule):
    pq = t._planned()
    assert pq is not None, t.explain()
    return [d for d in pq.decisions if d["rule"] == rule]


def test_planner_prunes_and_pushes(tab):
    t, rows = tab
    q = t.where("a > 44").groupBy("k", "sum(b) as sb")
    got = {r.k: r.sb for r in q.collect()}
    exp = {}
    for k, a, b, f, s in rows:
        if a > 44:
            exp[k] = exp.get(k, 0) + b
    assert got == exp
    pq = q._planned()
    assert pq.ok and pq.scan_stats["columns_read"] == {"k", "a", "b"}
    # chunk-skip: a is i%50 per 2000-row chunk, so no chunk can be
    # skipped on a>44 — but the ranges must have been extracted
    assert any("chunk-skip" in d["reason"] for d in
               _decisions(q, "pushdown-predicate"))


def test_planner_chunk_skip_actually_skips(ctx, tmp_path):
    # monotone column: most chunks provably cannot match
    rows = [(i, i % 3) for i in range(10000)]
    path = str(tmp_path / "mono")
    os.makedirs(path)
    _write(tmp_path / "mono", rows, ["x", "y"], "part-00000.tab",
           chunk_rows=1000)
    t = ctx.tabular(path).asTable("t")
    q = t.where("x >= 7500", "x < 7600")
    got = q.collect()
    assert len(got) == 100 and got[0].x == 7500
    pq = q._planned()
    assert pq.scan_stats["chunks_skipped"] >= 8, pq.scan_stats


def test_chunk_skip_int_literal_over_float_column(ctx, tmp_path):
    """Review regression: `f > 10` with an INT literal over a FLOAT
    column must not tighten the skip bound to 11 — a chunk whose max
    is 10.5 still matches."""
    from dpark_tpu.tabular import write_tabular
    rows = [(10.5, 1), (10.2, 2)]
    path = str(tmp_path / "fskip")
    os.makedirs(path)
    write_tabular(os.path.join(path, "part-00000.tab"), ["f", "v"],
                  rows, chunk_rows=10)
    t = ctx.tabular(path).asTable("t")
    q = t.where("f > 10")
    got = sorted((r.f, r.v) for r in q.collect())
    assert got == [(10.2, 2), (10.5, 1)]
    pq = q._planned()
    assert pq is not None and pq.scan_stats["chunks_skipped"] == 0
    # host parity
    from dpark_tpu import conf
    conf.QUERY_PLAN = False
    try:
        t2 = ctx.tabular(path).asTable("t")
        assert sorted((r.f, r.v)
                      for r in t2.where("f > 10").collect()) == got
    finally:
        conf.QUERY_PLAN = True


def test_runtime_fallback_recorded_from_count(ctx):
    """Review regression: a run-time plan failure via count()/take()
    records its reason for the lint rule, same as collect()."""
    t = ctx.parallelize([(True, 1), (False, 2)], 2).asTable("b v")
    q = t.groupBy("b", "sum(v) as sv")      # bool key fails at encode
    assert q.count() == 2                   # host path serves
    assert any("plan execution failed" in fb["reason"]
               for fb in getattr(q.rdd, "_query_fallbacks", ())), \
        getattr(q.rdd, "_query_fallbacks", None)


def test_scan_only_runs_no_job(ctx):
    ctx.start()
    t = ctx.parallelize([(i, i * 2) for i in range(1000)], 4) \
        .asTable("a b")
    before = len(ctx.scheduler.history)
    got = t.where("a % 2 == 0").select("b").collect()
    assert len(got) == 500 and got[1].b == 4
    assert t.where("a % 2 == 0").count() == 500
    # the scan-only query answered from the columnar scan: no job ran
    assert len(ctx.scheduler.history) == before


def test_planner_decline_reasons(ctx):
    t = ctx.parallelize([(1.5, 2, "x")] * 10, 2).asTable("f a s")
    # float group key: no device hash semantics
    q = t.groupBy("f", "sum(a) as sa")
    assert q._planned() is None
    assert any("float group" in fb["reason"]
               for fb in q.rdd._query_fallbacks)
    # string aggregate column
    q2 = t.groupBy("a", "min(s) as ms")
    assert q2._planned() is None
    assert any("string aggregate" in fb["reason"]
               for fb in q2.rdd._query_fallbacks)
    # non-device aggregate (adcount) keeps the host path, with reason
    q3 = t.groupBy("a", "adcount(s) as ds")
    assert q3._planned() is None
    assert any("non-device aggregate" in fb["reason"]
               for fb in q3.rdd._query_fallbacks)
    # results still correct through the host path
    assert q3.collect()[0].ds >= 1


def test_planner_int_sum_overflow_declines(ctx):
    big = 2 ** 55
    t = ctx.parallelize([(1, big), (1, big), (2, big)] * 200, 2) \
        .asTable("k v")
    q = t.groupBy("k", "sum(v) as sv")
    assert q._planned() is None
    assert any("overflow" in fb["reason"]
               for fb in q.rdd._query_fallbacks)
    got = {r.k: r.sv for r in q.collect()}      # host path: exact
    assert got[1] == 400 * big


def test_table_host_fallback_lint_rule(ctx):
    from dpark_tpu.analysis import lint_plan
    t = ctx.parallelize([(1.5, 2)] * 10, 2).asTable("f a")
    q = t.groupBy("f", "sum(a) as sa")
    assert q._planned() is None         # attaches _query_fallbacks
    report = lint_plan(q.rdd)
    finds = [x for x in report if x.rule == "table-host-fallback"]
    assert finds and "float group" in finds[0].message


def test_explain_text(ctx):
    t = ctx.parallelize([(i % 5, i) for i in range(100)], 2) \
        .asTable("k v")
    q = t.where("v > 10").groupBy("k", "sum(v) as sv")
    text = q.explain()
    assert "GroupAgg" in text and "prune-columns" in text
    assert "pushdown-predicate" in text


def test_count_only_group(ctx):
    """count(*)-only group-bys have no aggregate argument column —
    the planner synthesizes the value leaf."""
    t = ctx.parallelize([(i % 4, "u%d" % (i % 3)) for i in range(200)],
                        2).asTable("k s")
    q = t.groupBy("k", "count(*) as c")
    assert q._planned() is not None, q.explain()
    assert sorted((r.k, r.c) for r in q.collect()) == [
        (0, 50), (1, 50), (2, 50), (3, 50)]
    q2 = t.groupBy(["k", "s"], "count(*) as c")
    got = sorted(tuple(r) for r in q2.collect())
    exp = {}
    for i in range(200):
        exp[(i % 4, "u%d" % (i % 3))] = \
            exp.get((i % 4, "u%d" % (i % 3)), 0) + 1
    assert got == sorted((k, s, c) for (k, s), c in exp.items())


def test_bool_and_none_keys_keep_host_values(ctx):
    """Review regression: bool/None group keys must come back as their
    ORIGINAL values, not TokenDict-stringified 'True'/'None' — the
    encoder refuses non-str objects and the host path serves."""
    t = ctx.parallelize([(True, 1), (False, 2), (True, 3)], 2) \
        .asTable("flag v")
    got = sorted((r.flag, r.sv)
                 for r in t.groupBy("flag", "sum(v) as sv").collect())
    assert got == [(False, 2), (True, 4)]
    assert all(isinstance(k, bool) for k, _ in got)
    t2 = ctx.parallelize([("a", 1), (None, 2), ("a", 3)], 2) \
        .asTable("s v")
    got2 = {r.s: r.sv
            for r in t2.groupBy("s", "sum(v) as sv").collect()}
    assert got2 == {"a": 4, None: 2}
    assert None in got2


def test_count_col_null_semantics(ctx):
    """Review regression: count(col) skips None on the host — the
    device plan must decline object-column counts, not count rows."""
    t = ctx.parallelize([(1, "a"), (1, None), (2, "b")], 2) \
        .asTable("k s")
    q = t.groupBy("k", "count(s) as c")
    got = {r.k: r.c for r in q.collect()}
    assert got == {1: 1, 2: 1}
    assert q._planned() is None
    assert any("non-null" in fb["reason"]
               for fb in q.rdd._query_fallbacks)
    # numeric-argument counts can never see None: device plan rides
    q2 = t.groupBy("k", "count(k) as c")
    assert q2._planned() is not None
    assert {r.k: r.c for r in q2.collect()} == {1: 2, 2: 1}


def test_portable_hash_nan_inf_no_crash():
    import numpy as np
    from dpark_tpu.utils.phash import portable_hash
    for v in (float("nan"), float("inf"), float("-inf"),
              np.float64("nan"), np.float64("inf")):
        assert isinstance(portable_hash(v), int)
    assert portable_hash(float("nan")) == portable_hash(
        np.float64("nan"))


def test_mixed_chunk_dtypes_promote(ctx, tmp_path):
    """Review regression: a column whose chunks mix int and float
    resolves float64 for the whole scan (not the first chunk's int),
    so values match the host's numerically for every row."""
    from dpark_tpu.tabular import write_tabular
    rows = [(1, 10), (2, 20), (2.5, 30), (3.5, 40)]
    path = str(tmp_path / "mix")
    os.makedirs(path)
    write_tabular(os.path.join(path, "part-00000.tab"), ["x", "y"],
                  rows, chunk_rows=2)
    t = ctx.tabular(path).asTable("t")
    got = [r.q for r in t.select("x * 2 as q").collect()]
    assert got == [2.0, 4.0, 5.0, 7.0]
    # int-only expressions over the promoted column are FLOAT now —
    # // over floats declines, host path serves exactly
    q2 = t.select("x // 1 as q")
    assert [r.q for r in q2.collect()] == [1, 2, 2.0, 3.0]


def test_fallback_provenance_not_shared(ctx):
    """Review regression: one query's decline reason must not leak
    into sibling queries built from the same base table."""
    t = ctx.parallelize([(1.5, 2)] * 4, 2).asTable("f a")
    q1 = t.groupBy("f", "sum(a) as sa")     # float key: declines
    assert q1._planned() is None
    q2 = t.select("a")
    pq2 = q2._planned()
    assert pq2 is not None and not q2._plan_fallbacks


def test_query_knob_off_pins_host(ctx):
    from dpark_tpu import conf
    t = ctx.parallelize([(i % 3, i) for i in range(100)], 2) \
        .asTable("k v")
    q = t.groupBy("k", "sum(v) as sv")
    old = conf.QUERY_PLAN
    conf.QUERY_PLAN = False
    try:
        assert q._planned() is None
        assert sorted((r.k, r.sv) for r in q.collect()) == [
            (0, 1683), (1, 1617), (2, 1650)]
    finally:
        conf.QUERY_PLAN = old


def test_adapt_observes_query_path(ctx, tmp_path, monkeypatch):
    """Device runs of a planned query feed adapt decision point 2 with
    observed ms under the query-level signature."""
    from dpark_tpu import adapt
    monkeypatch.setenv("DPARK_ADAPT_DIR", str(tmp_path / "adapt"))
    adapt.configure(mode="observe", store_dir=str(tmp_path / "adapt"))
    try:
        t = ctx.parallelize([(i % 5, i) for i in range(2000)], 2) \
            .asTable("k v")
        q = t.groupBy("k", "sum(v) as sv")
        q.collect()
        pq = q._planned()
        assert pq is not None and pq.adapt_sig is not None
        hist = adapt.stage_history()
        key = "%s|%s" % pq.adapt_sig
        assert key in hist and hist[key].get("device_ms") is not None
    finally:
        adapt.configure(mode=None, store_dir=None)


# ---------------------------------------------------------------------------
# SQL literal escapes (satellite 6)
# ---------------------------------------------------------------------------

@pytest.fixture()
def quoted(ctx):
    rows = [("don't group by", 1), ("plain", 2), ("a, b", 3),
            ("don't", 4)]
    return ctx.parallelize(rows, 2).asTable("s v", name="q")


def test_sql_doubled_quote_escape_matches(ctx, quoted):
    got = ctx.sql("select * from q where s == 'don''t group by'",
                  q=quoted).collect()
    assert [(r.s, r.v) for r in got] == [("don't group by", 1)]
    got = ctx.sql("select v from q where s == 'don''t'",
                  q=quoted).collect()
    assert [r.v for r in got] == [4]


def test_sql_backslash_escape_still_works(ctx, quoted):
    got = ctx.sql(r"select v from q where s == 'don\'t'",
                  q=quoted).collect()
    assert [r.v for r in got] == [4]


def test_sql_comma_inside_literal_does_not_split(ctx, quoted):
    got = ctx.sql("select v from q where s == 'a, b'",
                  q=quoted).collect()
    assert [r.v for r in got] == [3]
    # and in a select list: the literal comma must not split columns
    t = quoted.where("s == 'a, b'")
    assert [r.v for r in t.collect()] == [3]


def test_split_cols_quote_aware():
    from dpark_tpu.table import _split_cols
    assert _split_cols(("a, b",)) == ["a", "b"]
    assert _split_cols(("s == 'x, y', v",)) == ["s == 'x, y'", "v"]
    assert _split_cols(("s == 'it''s, fine', v",)) == \
        ["s == 'it''s, fine'", "v"]


def test_mask_literals_doubled_quotes():
    from dpark_tpu.table import _mask_literals
    masked = _mask_literals("where s == 'don''t group by' limit 3")
    assert "group by" not in masked.replace("x", "")
    assert len(masked) == len("where s == 'don''t group by' limit 3")
    assert masked.endswith("limit 3")


# ---------------------------------------------------------------------------
# device acceptance (2-device mesh: runs anywhere)
# ---------------------------------------------------------------------------

def test_select_filter_group_all_array_tpu(tmp_path):
    """ISSUE 13 acceptance shape: a select+filter+group-by query over
    tabular input runs all-array end to end — every stage kind
    "array", no fallback_reason, and the scan read only the referenced
    columns."""
    from dpark_tpu import DparkContext
    rows = [(i % 53, i % 50, i % 7, (i % 13) * 0.5, "s%d" % (i % 5))
            for i in range(30000)]
    path = str(tmp_path / "tab")
    os.makedirs(path)
    _write(tmp_path / "tab", rows, ["k", "a", "b", "f", "s"],
           "part-00000.tab", chunk_rows=4000)
    tctx = DparkContext("tpu:2")
    tctx.start()
    try:
        t = tctx.tabular(path).asTable("t")
        q = t.where("a > 10").groupBy(
            "k", "sum(b) as sb", "count(*) as c", "avg(f) as af")
        n0 = len(tctx.scheduler.history)
        got = sorted(q.collect())
        exp = {}
        for k, a, b, f, s in rows:
            if a > 10:
                sb, c, sf = exp.get(k, (0, 0, 0))
                exp[k] = (sb + b, c + 1, sf + f)
        assert got == sorted((k, sb, c, sf / c)
                             for k, (sb, c, sf) in exp.items())
        pq = q._planned()
        assert pq.ok and pq.scan_stats["columns_read"] == \
            {"k", "a", "b", "f"}
        recs = tctx.scheduler.history[n0:]
        assert recs, "planned query ran no job"
        for rec in recs:
            for st in rec.get("stage_info", []):
                assert str(st.get("kind", "")).startswith("array"), st
                assert not st.get("fallback_reason"), st
    finally:
        tctx.stop()


def test_string_group_key_rides_encoded_tpu():
    from dpark_tpu import DparkContext
    rows = [("g%d" % (i % 11), i % 100) for i in range(20000)]
    tctx = DparkContext("tpu:2")
    tctx.start()
    try:
        t = tctx.parallelize(rows, 2).asTable("s v")
        q = t.groupBy("s", "sum(v) as sv", "count(*) as c")
        got = sorted(q.collect())
        exp = {}
        for s, v in rows:
            sv, c = exp.get(s, (0, 0))
            exp[s] = (sv + v, c + 1)
        assert got == sorted((s, sv, c)
                             for s, (sv, c) in exp.items())
        rec = tctx.scheduler.history[-1]
        for st in rec.get("stage_info", []):
            assert str(st.get("kind", "")).startswith("array"), st
        assert any(d["rule"] == "encode-strings"
                   for d in q._planned().decisions)
    finally:
        tctx.stop()
