"""Exact monoid classification (round-1 advisor fix, fuse.py classify_merge):
only provable matches may replace the user's merge function with a segment
scatter.  A deliberately-misclassifiable merge (saturating add) must stay
unclassified AND produce the correct, host-parity answer on the tpu master."""

import operator

import numpy as np
import pytest

from dpark_tpu.backend.tpu.fuse import classify_merge


SAT = 10 ** 6


def test_direct_callables():
    assert classify_merge(operator.add) == "add"
    assert classify_merge(operator.mul) == "mul"
    assert classify_merge(min) == "min"
    assert classify_merge(max) == "max"
    assert classify_merge(np.add) == "add"
    assert classify_merge(np.maximum) == "max"


def test_canonical_lambdas():
    assert classify_merge(lambda a, b: a + b) == "add"
    assert classify_merge(lambda x, y: x + y) == "add"       # arg names
    assert classify_merge(lambda a, b: b + a) == "add"
    assert classify_merge(lambda a, b: a * b) == "mul"
    assert classify_merge(lambda a, b: min(a, b)) == "min"
    assert classify_merge(lambda a, b: max(a, b)) == "max"

    def named(u, v):
        return u + v
    assert classify_merge(named) == "add"


def test_saturating_add_not_classified():
    # agrees with + on small values; the old probabilistic probe
    # classified it as "add" and silently saturated nothing
    assert classify_merge(lambda a, b: min(a + b, SAT)) is None


def test_non_monoid_forms_not_classified():
    assert classify_merge(lambda a, b: a - b) is None
    assert classify_merge(lambda a, b: a + b + 1) is None
    assert classify_merge(lambda a, b, c=0: a + b) is None   # 3 params
    assert classify_merge(lambda *a: sum(a)) is None
    assert classify_merge("not callable") is None

    captured = 0
    assert classify_merge(lambda a, b: a + b + captured) is None


def test_shadowed_builtin_not_classified():
    ns = {"min": lambda a, b: a * b}      # min shadowed: not provable
    exec("def f(a, b):\n    return min(a, b)", ns)
    assert classify_merge(ns["f"]) is None


def test_custom_builtins_dict_not_classified():
    # shadowing through a custom __builtins__ dict must also be caught
    ns = {"__builtins__": {"min": lambda a, b: a * b}}
    exec("def f(a, b):\n    return min(a, b)", ns)
    assert ns["f"](3, 4) == 12
    assert classify_merge(ns["f"]) is None


def test_explicit_hint():
    def weird_but_add(a, b):
        return sum([a, b])
    assert classify_merge(weird_but_add) is None
    weird_but_add.__dpark_monoid__ = "add"
    assert classify_merge(weird_but_add) == "add"


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


@pytest.mark.mesh
def test_saturating_add_correct_on_tpu(tctx):
    """End-to-end: the misclassifiable merge gets the right answer."""
    from dpark_tpu import DparkContext
    sat_add = lambda a, b: min(a + b, SAT)          # noqa: E731
    pairs = [(i % 5, SAT // 3) for i in range(60)]  # sums would exceed SAT
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(sat_add, 8).collect())
    lctx = DparkContext("local")
    expect = dict(lctx.parallelize(pairs, 8)
                  .reduceByKey(sat_add, 8).collect())
    lctx.stop()
    assert got == expect
    assert all(v <= SAT for v in got.values())
