"""Chaos plane (ISSUE 5): deterministic fault injection + recovery
parity.

The suite proves recovery is EXERCISED, not assumed: real jobs
(reduceByKey, groupByKey().mapValue, join, a dstream window) run under
injected faults — fetch failures, spill corruption, device OOM, disk
full, checkpoint write errors — with fixed seeds, and every result is
asserted BIT-IDENTICAL to the clean run while the job record shows the
expected recovery events (parent resubmit / recompute / per-stage
degrade_reason).  No job aborts.

Device tests run on a 2-device sliced mesh ("tpu:2") so the suite
works on small containers (see the `mesh` marker note in conftest)."""

import operator
import os
import random
import time

import numpy as np
import pytest

from dpark_tpu import conf, faults
from dpark_tpu.shuffle import (FetchFailed, SpillCorruption,
                               SpillWriteError)


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends without an installed chaos plane."""
    faults.configure(None)
    yield
    faults.configure(None)


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def tiny_waves():
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 500
    yield
    conf.STREAM_CHUNK_ROWS = old


def _recovery(sched):
    return sched.recovery_summary()


# ---------------------------------------------------------------------------
# the plane itself: grammar, determinism, corruption
# ---------------------------------------------------------------------------

def test_spec_grammar():
    plane = faults.configure(
        "shuffle.fetch:p=0.2,seed=7;executor.dispatch:nth=3,kind=oom")
    specs = plane.specs
    assert set(specs) == {"shuffle.fetch", "executor.dispatch"}
    assert specs["shuffle.fetch"].p == 0.2
    assert specs["shuffle.fetch"].seed == 7
    assert specs["executor.dispatch"].nth == 3
    assert specs["executor.dispatch"].kind == "oom"


def test_spec_rejects_unknown_site_and_kind():
    with pytest.raises(ValueError):
        faults.configure("shuffle.fetchx:nth=1")
    with pytest.raises(ValueError):
        faults.configure("shuffle.fetch:kind=explode")


def test_seeded_probability_is_deterministic():
    def pattern():
        faults.configure("shuffle.fetch:p=0.5,seed=7")
        out = []
        for _ in range(32):
            try:
                faults.hit("shuffle.fetch")
                out.append(0)
            except OSError:
                out.append(1)
        return out

    first, second = pattern(), pattern()
    assert first == second
    assert 1 in first and 0 in first        # p=0.5 over 32 draws


def test_nth_fires_exactly_once():
    faults.configure("executor.dispatch:nth=3")
    fired = []
    for i in range(10):
        try:
            faults.hit("executor.dispatch")
        except OSError:
            fired.append(i)
    assert fired == [2]
    st = faults.stats()["executor.dispatch"]
    assert st["hits"] == 10 and st["fired"] == 1


def test_bare_spec_fires_once_and_times_caps():
    faults.configure("shuffle.fetch")
    with pytest.raises(OSError):
        faults.hit("shuffle.fetch")
    faults.hit("shuffle.fetch")             # exhausted: no-op
    faults.configure("executor.dispatch:p=1,times=2")
    fired = 0
    for _ in range(5):
        try:
            faults.hit("executor.dispatch")
        except OSError:
            fired += 1
    assert fired == 2


def test_corrupt_preserves_length_and_oom_shape():
    faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
    blob = bytes(range(64))
    out = faults.hit("shuffle.spill_write", blob)
    assert len(out) == len(blob) and out != blob
    assert faults.hit("shuffle.spill_write", blob) == blob   # once
    faults.configure("executor.dispatch:nth=1,kind=oom")
    with pytest.raises(Exception) as e:
        faults.hit("executor.dispatch")
    assert "RESOURCE_EXHAUSTED" in str(e.value)
    from dpark_tpu.backend.tpu import _device_error
    assert _device_error(e.value)


def test_inactive_plane_is_passthrough():
    assert not faults.active()
    blob = b"xyz"
    assert faults.hit("shuffle.fetch", blob) is blob
    assert faults.stats() == {}


# ---------------------------------------------------------------------------
# chaos parity: fetch failure (host path)
# ---------------------------------------------------------------------------

def _reduce_job(ctx):
    return sorted(ctx.parallelize([(i % 7, i) for i in range(210)], 4)
                  .reduceByKey(operator.add, 3).collect())


def _group_job(ctx):
    # 150 distinct keys over 3 reduce partitions: ~50 keys per reduce
    # task, above the forced DiskSpillMerger threshold in the spill
    # tests (max_items = SHUFFLE_CHUNK_RECORDS * 4 = 32)
    return sorted(
        ctx.parallelize([(i % 150, i % 5) for i in range(600)], 4)
        .groupByKey(3).mapValue(lambda vs: tuple(sorted(vs)))
        .collect())


def _join_job(ctx):
    a = ctx.parallelize([(i % 6, i) for i in range(60)], 3)
    b = ctx.parallelize([(i % 6, i * 10) for i in range(30)], 2)
    return sorted(a.join(b, 3).collect())


def test_fetch_fault_reduce_parity(ctx):
    clean = _reduce_job(ctx)
    faults.configure("shuffle.fetch:nth=1")
    got = _reduce_job(ctx)
    assert got == clean
    st = faults.stats()["shuffle.fetch"]
    assert st["fired"] == 1
    rec = ctx.scheduler.history[-1]
    assert rec["state"] == "done"
    assert rec.get("resubmits", 0) >= 1         # parent stage re-ran


def test_fetch_fault_join_parity(ctx):
    clean = _join_job(ctx)
    faults.configure("shuffle.fetch:nth=2")
    got = _join_job(ctx)
    assert got == clean
    assert faults.stats()["shuffle.fetch"]["fired"] == 1
    rec = ctx.scheduler.history[-1]
    assert rec["state"] == "done"
    assert rec.get("resubmits", 0) >= 1


def test_fetch_fault_probabilistic_parity(ctx):
    """Seeded p= injection across a multi-fetch job still converges to
    the exact clean result (each retry redraws deterministically)."""
    clean = _reduce_job(ctx)
    faults.configure("shuffle.fetch:p=0.3,seed=11,times=3")
    got = _reduce_job(ctx)
    assert got == clean
    assert ctx.scheduler.history[-1]["state"] == "done"


# ---------------------------------------------------------------------------
# chaos parity: spill corruption -> crc32c -> FetchFailed -> recompute
# ---------------------------------------------------------------------------

def test_spill_corruption_group_parity(ctx):
    """A corrupted host spill chunk (DiskSpillMerger) surfaces as
    FetchFailed via its crc32c frame; the consuming stage recomputes
    (the parent's outputs are intact) and the result is bit-identical
    — never unpickled garbage."""
    old = conf.SHUFFLE_CHUNK_RECORDS
    conf.SHUFFLE_CHUNK_RECORDS = 8          # max_items 32: force spills
    try:
        clean = _group_job(ctx)
        faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
        got = _group_job(ctx)
        assert got == clean
        assert faults.stats()["shuffle.spill_write"]["fired"] == 1
        rec = ctx.scheduler.history[-1]
        assert rec["state"] == "done"
        assert rec.get("recomputes", 0) >= 1    # intact-parent retry
    finally:
        conf.SHUFFLE_CHUNK_RECORDS = old


def test_disk_spill_merger_crc_detects_corruption(tmp_path):
    from dpark_tpu.dependency import Aggregator
    from dpark_tpu.shuffle import DiskSpillMerger
    agg = Aggregator(lambda v: v, operator.add, operator.add)

    def build(shuffle_id):
        m = DiskSpillMerger(agg, max_items=10, workdir=str(tmp_path),
                            shuffle_id=shuffle_id, reduce_id=2)
        for _ in range(4):
            m.merge([(k, 1) for k in range(25)])
        return m

    # clean round trip first
    assert dict(build(None)) == {k: 4 for k in range(25)}
    # corrupt one chunk: tagged merger raises FetchFailed for lineage
    faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
    m = build(7)
    with pytest.raises(FetchFailed) as e:
        dict(m)
    assert e.value.shuffle_id == 7 and e.value.reduce_id == 2
    assert isinstance(e.value.__cause__, SpillCorruption)
    # untagged merger: a plain (task-failing) corruption error
    faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
    with pytest.raises(SpillCorruption):
        dict(build(None))


def test_executor_run_crc_round_trip(tmp_path):
    from dpark_tpu.backend.tpu.executor import JAXExecutor
    p = str(tmp_path / "run")
    cols = [np.arange(100, dtype=np.int64), np.ones(100)]
    JAXExecutor._write_run(p, cols)
    back = JAXExecutor._read_run(p)
    assert np.array_equal(back[0], cols[0])
    faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
    JAXExecutor._write_run(p, cols)
    with pytest.raises(SpillCorruption, match="crc32c"):
        JAXExecutor._read_run(p)


# ---------------------------------------------------------------------------
# chaos parity: device path (tpu master)
# ---------------------------------------------------------------------------

def _device_reduce(ctx):
    from dpark_tpu import Columns
    i = np.arange(20000, dtype=np.int64)
    data = Columns((i * 2654435761) % 997, i % 11)
    return sorted(ctx.parallelize(data, 2)
                  .reduceByKey(operator.add, 2).collect())


def _degrade_reasons(sched):
    return sched.degrade_reasons()


def _join_premergers(ex):
    """Wait out background premerge walkers from PREVIOUS runs on this
    executor so a freshly configured chaos plane cannot be consumed by
    a stale store's merged-run writes."""
    for s in list(ex.shuffle_store.values()):
        pm = s.get("premerge")
        if pm is not None and pm._thread is not None:
            pm._thread.join(timeout=10)


def test_device_oom_halved_wave_retry_parity(tctx2, tiny_waves):
    """An injected device OOM on a stage dispatch retries the stage
    with a HALVED wave budget; the job completes bit-identically and
    the stage records a degrade_reason — never a job abort."""
    clean = _device_reduce(tctx2)
    faults.configure("executor.dispatch:nth=1,kind=oom")
    got = _device_reduce(tctx2)
    assert got == clean
    assert faults.stats()["executor.dispatch"]["fired"] == 1
    reasons = _degrade_reasons(tctx2.scheduler)
    assert any("halved wave budget" in r for r in reasons), reasons
    assert tctx2.scheduler.history[-1]["state"] == "done"


def test_device_oom_object_fallback_parity(tctx2, tiny_waves):
    """A persistent device OOM (first attempt AND the halved-wave
    retry) degrades the stage to the OBJECT PATH only — results stay
    bit-identical and degrade_reason says why."""
    clean = _device_reduce(tctx2)
    faults.configure("executor.dispatch:p=1,times=2,kind=oom")
    got = _device_reduce(tctx2)
    assert got == clean
    assert faults.stats()["executor.dispatch"]["fired"] == 2
    reasons = _degrade_reasons(tctx2.scheduler)
    assert any("object path" in r for r in reasons), reasons
    assert tctx2.scheduler.history[-1]["state"] == "done"


def test_compile_fault_degrades_to_object_path(tctx2, tiny_waves):
    """A failure at the compile site (not a device runtime error)
    falls back to the object path for the stage, recorded.  The
    faulted run goes FIRST — a prior clean run would warm the program
    cache and the compile site (hit per cache miss) would never
    fire."""
    faults.configure("executor.compile:nth=1")
    got = _device_reduce(tctx2)
    assert faults.stats()["executor.compile"]["fired"] == 1
    reasons = _degrade_reasons(tctx2.scheduler)
    assert any("array path error" in r for r in reasons), reasons
    faults.configure(None)
    assert got == _device_reduce(tctx2)


def test_device_spill_corruption_recomputes_stage(tctx2, tiny_waves):
    """A corrupted device spill RUN (the streamed no-combine path)
    fails its crc32c at export, surfaces as FetchFailed on the hbm
    uri, and the WHOLE parent device stage recomputes (a device stage
    computes every partition in one program) — parity holds."""
    def job():
        from dpark_tpu import Columns
        keys = np.arange(15000, dtype=np.int64) % 97
        vals = np.arange(15000, dtype=np.int64) % 13
        return {k: sorted(v) for k, v in
                tctx2.parallelize(Columns(keys, vals), 2)
                .groupByKey(8).collect()}

    clean = job()
    _join_premergers(tctx2.scheduler.executor)
    faults.configure("shuffle.spill_write:nth=3,kind=corrupt")
    got = job()
    assert got == clean
    assert faults.stats()["shuffle.spill_write"]["fired"] == 1
    summary = _recovery(tctx2.scheduler)
    assert summary["resubmits"] >= 1 or summary["recomputes"] >= 1, \
        summary
    assert tctx2.scheduler.history[-1]["state"] == "done"


def test_device_spill_disk_full_is_task_failure(tctx2, tiny_waves):
    """ENOSPC during the background spill surfaces on the consuming
    stage as TASK failures (retry/escalate through the scheduler's
    accounting), the partial chunk is cleaned up, and the retried
    tasks complete the job on the object path."""
    from dpark_tpu.env import env

    def job():
        from dpark_tpu import Columns
        rng = np.random.RandomState(17)
        # UNIQUE keys: equal-key tie order may legitimately differ
        # between the device path and the object-path retry
        keys = rng.permutation(12000).astype(np.int64)
        vals = np.arange(12000, dtype=np.int64)
        return tctx2.parallelize(Columns(keys, vals), 2) \
            .sortByKey(numSplits=8).collect()

    clean = job()
    _join_premergers(tctx2.scheduler.executor)
    faults.configure("shuffle.spill_write:nth=1,kind=enospc")
    got = job()
    assert got == clean
    assert faults.stats()["shuffle.spill_write"]["fired"] == 1
    summary = _recovery(tctx2.scheduler)
    assert summary["retries"] >= 1, summary
    assert any("spill write failed" in r
               for r in summary["reasons"]), summary
    # no partial chunk files left in any spool dir
    spool_root = os.path.join(env.workdir, "hbmruns")
    if os.path.isdir(spool_root):
        for root, _, files in os.walk(spool_root):
            for f in files:
                # every surviving run must read back clean
                from dpark_tpu.backend.tpu.executor import JAXExecutor
                JAXExecutor._read_run(os.path.join(root, f))


def test_spill_writer_cleans_partial_file(tmp_path):
    """The background writer unlinks a partially-written chunk when
    the write fails (faked full filesystem) and surfaces the error on
    the consumer, not the writer thread."""
    from dpark_tpu.backend.tpu.executor import _SpillWriter

    def partial_write(path, cols):
        with open(path, "wb") as f:
            f.write(b"partial")
        raise OSError(28, "No space left on device")

    w = _SpillWriter(partial_write)
    p1 = str(tmp_path / "r1")
    w.put(p1, [np.arange(3)])
    with pytest.raises(OSError):
        for _ in range(100):
            w.put(str(tmp_path / "r2"), [np.arange(3)])
            time.sleep(0.02)
        w.finish()
    w.abort()
    assert not os.path.exists(p1), "partial chunk file left behind"


# ---------------------------------------------------------------------------
# chaos parity: dstream window
# ---------------------------------------------------------------------------

def test_window_job_parity_under_fetch_fault(ctx):
    """A dstream reduceByKeyAndWindow run recovers from an injected
    fetch failure mid-stream with per-batch outputs identical to the
    clean run."""
    from dpark_tpu.dstream import StreamingContext

    batches = [[("k", 1), ("j", 2)], [("k", 2)], [("k", 4), ("j", 1)],
               [("k", 8)]]

    def run():
        ssc = StreamingContext(ctx, 1.0)
        out = []
        q = ssc.queueStream([list(b) for b in batches])
        q.reduceByKeyAndWindow(operator.add, 2.0).collect_batches(out)
        ssc.ctx.start()
        for ins in ssc.input_streams:
            ins.start()
        ssc.zero_time = 1000.0
        for k in range(1, len(batches) + 1):
            ssc.run_batch(1000.0 + k * ssc.batch_duration)
        return [(t, sorted(v)) for t, v in out]

    clean = run()
    faults.configure("shuffle.fetch:nth=2")
    got = run()
    assert got == clean
    assert faults.stats()["shuffle.fetch"]["fired"] == 1


# ---------------------------------------------------------------------------
# checkpoint.write site
# ---------------------------------------------------------------------------

def test_checkpoint_write_fault_retries(ctx, tmp_path):
    ctx.setCheckpointDir(str(tmp_path / "ckpt"))
    r = ctx.parallelize(range(40), 4).map(lambda x: x * 3)
    r.checkpoint()
    clean = list(range(0, 120, 3))
    faults.configure("checkpoint.write:nth=1")
    assert r.collect() == clean
    assert faults.stats()["checkpoint.write"]["fired"] == 1
    rec = ctx.scheduler.history[-1]
    assert rec["state"] == "done" and rec.get("retries", 0) >= 1
    # the checkpoint completed despite the injected failure: a fresh
    # read comes from the part files (lineage truncated)
    assert r.collect() == clean
    assert r._checkpoint_rdd is not None


# ---------------------------------------------------------------------------
# MAX_STAGE_FAILURES: bounded lineage recovery
# ---------------------------------------------------------------------------

def test_stage_failure_cap_aborts_with_chained_error(ctx):
    """A PERSISTENTLY failing fetch aborts the job after
    conf.MAX_STAGE_FAILURES lineage-recovery rounds with the real
    fetch error chained — instead of resubmitting the parent stage
    forever."""
    faults.configure("shuffle.fetch:p=1")       # every fetch fails
    r = ctx.parallelize([(i % 3, 1) for i in range(30)], 2) \
           .reduceByKey(operator.add, 2)
    with pytest.raises(RuntimeError) as e:
        r.collect()
    assert "MAX_STAGE_FAILURES" in str(e.value)
    assert isinstance(e.value.__cause__, FetchFailed)
    rec = ctx.scheduler.history[-1]
    assert rec["state"] == "aborted"
    assert rec.get("resubmits", 0) == conf.MAX_STAGE_FAILURES


# ---------------------------------------------------------------------------
# dcn connect: bounded retry with exponential backoff + jitter
# ---------------------------------------------------------------------------

def test_backoff_schedule_fake_clock():
    from dpark_tpu import dcn
    delays = list(dcn.backoff_delays(5, base=0.1,
                                     rand=random.Random(0)))
    assert len(delays) == 4
    for k, d in enumerate(delays):
        span = 0.1 * (2 ** k)
        assert span / 2 <= d <= span, (k, d)
    # deterministic under the same rand seed
    again = list(dcn.backoff_delays(5, base=0.1,
                                    rand=random.Random(0)))
    assert delays == again


def test_connect_retries_transient_then_succeeds(tmp_path):
    """An injected transient connect failure is retried with backoff
    (fake clock records the sleeps) and the fetch then succeeds."""
    from dpark_tpu import dcn
    from dpark_tpu.dcn import BucketServer
    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    srv = BucketServer(wd, host="127.0.0.1").start()
    slept = []
    try:
        uri = "tcp://%s:%d" % srv.bind_address
        faults.configure("dcn.connect:nth=1")
        sock = dcn._connect(uri, 5, attempts=3, sleep=slept.append,
                            rand=random.Random(3))
        sock.close()
        assert len(slept) == 1 and slept[0] > 0
        assert faults.stats()["dcn.connect"]["fired"] == 1
    finally:
        srv.stop()


def test_connect_exhausts_attempts_and_raises(tmp_path):
    from dpark_tpu import dcn
    slept = []
    faults.configure("dcn.connect:p=1")
    with pytest.raises(OSError):
        dcn._connect("tcp://127.0.0.1:1", 1, attempts=3,
                     sleep=slept.append, rand=random.Random(1))
    assert len(slept) == 2                  # attempts-1 backoffs
    assert slept[1] > slept[0] / 2          # exponential-ish growth


def test_server_error_stays_non_retryable(tmp_path):
    """The application-level ServerError classification is preserved:
    a status-1 response raises once, with no connect retries."""
    from dpark_tpu import dcn
    from dpark_tpu.dcn import BucketServer, ServerError
    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    srv = BucketServer(wd, host="127.0.0.1").start()
    try:
        uri = "tcp://%s:%d" % srv.bind_address
        pool = dcn.FetchPool()
        with pytest.raises(ServerError):
            pool.fetch(uri, ("no-such-kind",))
        pool.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# speculation / retry accounting + hostatus decay (ISSUE 5 satellite)
# ---------------------------------------------------------------------------

def test_speculation_first_result_wins_no_double_count(pctx):
    """An injected straggler triggers a speculative duplicate; the
    first completion wins, the duplicate never double-counts in the
    job record, and the result is exact."""
    def straggle(i, it):
        import time as _t
        items = list(it)
        if i == 0:
            _t.sleep(4)
        return [sum(items)]

    old = (conf.SPECULATION_MULTIPLIER, conf.SPECULATION_QUANTILE)
    conf.SPECULATION_MULTIPLIER = 1.5
    conf.SPECULATION_QUANTILE = 0.5
    try:
        got = pctx.parallelize(list(range(100)), 10) \
                  .mapPartitionsWithIndex(straggle).collect()
        assert sum(got) == 4950
        rec = pctx.scheduler.history[-1]
        assert rec.get("speculated", 0) >= 1
        # the duplicate's completion must not double-count
        assert rec["finished"] == rec["parts"] == 10
        assert rec["state"] == "done"
        # per-task records carry at most one SUCCESS per partition
        for st in rec["stage_info"]:
            by_part = {}
            for t in st.get("tasks", ()):
                if t["ok"]:
                    by_part[t["p"]] = by_part.get(t["p"], 0) + 1
            assert all(n == 1 for n in by_part.values()), by_part
    finally:
        conf.SPECULATION_MULTIPLIER, conf.SPECULATION_QUANTILE = old


def test_blacklisted_host_recovers_after_decay():
    """hostatus blacklisting is a RECENT-failure view: after the purge
    window elapses the host is offered work again."""
    from dpark_tpu.hostatus import TaskHostManager
    hm = TaskHostManager(purge_elapsed=60)
    t0 = 1000.0
    for _ in range(4):
        hm.task_failed_on("bad-host", now=t0)
    assert hm.is_blacklisted("bad-host", now=t0 + 1)
    ranked = hm.rank_hosts(["bad-host", "good-host"], now=t0 + 1)
    assert ranked[0] == "good-host"
    # decay: past the purge horizon the failures age out
    assert not hm.is_blacklisted("bad-host", now=t0 + 61)
    assert hm.offer_choice(["bad-host"], now=t0 + 61) == "bad-host"


# ---------------------------------------------------------------------------
# unbounded-recovery lint rule
# ---------------------------------------------------------------------------

def test_unbounded_recovery_rule_fires_only_under_injection(ctx):
    from dpark_tpu.analysis import lint_plan
    old = conf.LINT_WIDE_DEPTH
    conf.LINT_WIDE_DEPTH = 1
    try:
        r = ctx.parallelize([(i % 5, 1) for i in range(50)], 2) \
               .reduceByKey(operator.add, 2) \
               .map(lambda kv: (kv[1], kv[0])) \
               .reduceByKey(operator.add, 2)
        rules = {f.rule for f in lint_plan(r)}
        assert "unbounded-recovery" not in rules     # no injection
        faults.configure("shuffle.fetch:p=0.1,seed=1")
        rules = {f.rule for f in lint_plan(r)}
        assert "unbounded-recovery" in rules
        # a checkpoint pin silences it
        faults.configure("shuffle.fetch:p=0.1,seed=1")
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            mid = ctx.parallelize([(i % 5, 1) for i in range(50)], 2) \
                     .reduceByKey(operator.add, 2).checkpoint(d)
            top = mid.map(lambda kv: (kv[1], kv[0])) \
                     .reduceByKey(operator.add, 2)
            rules = {f.rule for f in lint_plan(top)}
            assert "unbounded-recovery" not in rules
    finally:
        conf.LINT_WIDE_DEPTH = old


# ---------------------------------------------------------------------------
# recovery summary plumbing (bench's faults/degrades sections)
# ---------------------------------------------------------------------------

def test_recovery_summary_shape(ctx):
    faults.configure("shuffle.fetch:nth=1")
    _reduce_job(ctx)
    summary = ctx.scheduler.recovery_summary()
    for field in ("resubmits", "recomputes", "retries", "fetch_failed",
                  "speculated", "reasons", "faults"):
        assert field in summary, summary
    assert summary["fetch_failed"] >= 1
    assert summary["faults"]["shuffle.fetch"]["fired"] == 1


# ---------------------------------------------------------------------------
# kill kind + spec-parse edge cases (ISSUE 20 satellite)
# ---------------------------------------------------------------------------

def test_kill_kind_hard_exits_subprocess():
    """kind=kill is os._exit(137) at the site — no atexit, no finally
    — proven in a subprocess (this process must survive the test)."""
    plane = faults.configure("shuffle.fetch:nth=2,kind=kill")
    assert plane.specs["shuffle.fetch"].kind == "kill"
    faults.configure(None)
    import subprocess
    import sys
    code = ("from dpark_tpu import faults\n"
            "faults.configure('shuffle.fetch:nth=1,kind=kill')\n"
            "faults.hit('shuffle.fetch')\n"
            "print('survived')\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 137, (proc.returncode, proc.stderr)
    assert "survived" not in proc.stdout


def test_spec_parse_edge_cases():
    # empty / separator-only specs install nothing
    assert faults.parse_spec("") == {}
    assert faults.parse_spec(None) == {}
    assert faults.parse_spec(";;") == {}
    # trailing comma and whitespace are tolerated
    specs = faults.parse_spec(" shuffle.fetch : nth=2 , kind=delay ,")
    assert specs["shuffle.fetch"].nth == 2
    assert specs["shuffle.fetch"].kind == "delay"
    # duplicate site: last spec wins (one spec per site)
    specs = faults.parse_spec("shuffle.fetch:nth=1;shuffle.fetch:nth=9")
    assert specs["shuffle.fetch"].nth == 9
    # malformed params fail loudly — a typo'd chaos run must never
    # silently inject nothing
    with pytest.raises(ValueError):
        faults.parse_spec("shuffle.fetch:nth")         # no '='
    with pytest.raises(ValueError):
        faults.parse_spec("shuffle.fetch:nth=x")       # non-numeric
    with pytest.raises(ValueError):
        faults.parse_spec("shuffle.fetch:kind=kaboom")  # unknown kind
    with pytest.raises(ValueError):
        faults.parse_spec("no.such.site:nth=1")        # unknown site


def test_stats_counters_are_thread_safe():
    """Concurrent hits from fetcher threads must never lose counts
    (the hit bookkeeping runs under the plane lock)."""
    import threading
    faults.configure("shuffle.fetch:p=0,seed=1")   # counts, never fires

    def worker():
        for _ in range(1000):
            faults.hit("shuffle.fetch")

    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    st = faults.stats()["shuffle.fetch"]
    assert st["hits"] == 8000 and st["fired"] == 0
