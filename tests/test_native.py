"""C++ native kernels: build, bind, and agree with the Python/jnp paths."""

import numpy as np
import pytest

from dpark_tpu import native
from dpark_tpu.utils.phash import portable_hash


def test_library_builds():
    assert native.get_lib() is not None, "g++ build failed"


def test_phash_bulk_matches_python():
    keys = np.array([0, 1, -1, 2**31 - 1, -(2**31), 2**62, -(2**62), 42],
                    dtype=np.int64)
    got = native.phash_i64_bulk(keys)
    expect = [portable_hash(int(k)) for k in keys]
    assert got.tolist() == expect


def test_phash_bytes_matches_python():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("no native lib")
    for s in [b"", b"a", b"hello world", "第三行".encode()]:
        assert lib.phash_bytes(s, len(s)) == portable_hash(s)


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert native.crc32c(b"123456789") == 0xE3069283
    # python fallback agrees
    lib_val = native.crc32c(b"dpark")
    import dpark_tpu.native as n
    saved, n._lib, n._tried = n._lib, None, True
    try:
        assert native.crc32c(b"dpark") == lib_val
    finally:
        n._lib, n._tried = saved, True


def test_split_lines():
    buf = b"one\ntwo\r\nthree\nlast-no-newline"
    starts, lens = native.split_lines(buf)
    lines = [buf[s:s + l] for s, l in zip(starts, lens)]
    assert lines == [b"one", b"two", b"three", b"last-no-newline"]

    starts, lens = native.split_lines(b"trailing\n")
    assert [buf2 for buf2 in
            [b"trailing"[s:s + l] for s, l in zip(starts, lens)]] \
        == [b"trailing"]


def test_tokendict_roundtrip():
    d = native.TokenDict()
    ids1 = d.encode("the quick brown fox the lazy dog the")
    assert len(ids1) == 8
    assert ids1[0] == ids1[4] == ids1[7]          # 'the' stable id
    ids2 = d.encode("fox dog unseen")
    assert ids2[0] == ids1[3]                     # 'fox'
    assert d.decode(int(ids1[0])) == "the"
    assert d.decode(int(ids2[2])) == "unseen"
    assert len(d) == 7


def test_tokendict_large():
    d = native.TokenDict()
    text = " ".join("w%d" % (i % 1000) for i in range(50000))
    ids = d.encode(text)
    assert len(ids) == 50000
    assert len(d) == 1000
    counts = np.bincount(ids)
    assert counts.sum() == 50000 and counts.max() == 50
