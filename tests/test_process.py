"""Same RDD semantics through the fork-pool process master — exercises
closure shipping, map-output snapshots and cross-process shuffle files
(reference style: test bodies re-run with -m process, SURVEY.md section 4).
"""


def test_collect_map(pctx):
    r = pctx.parallelize(range(100), 8)
    assert r.map(lambda x: x * 3).collect() == [x * 3 for x in range(100)]


def test_shuffle_reduce_by_key(pctx):
    pairs = [(i % 7, i) for i in range(1000)]
    got = dict(pctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 4).collect())
    expect = {}
    for k, v in pairs:
        expect[k] = expect.get(k, 0) + v
    assert got == expect


def test_closure_capture_across_process(pctx):
    base = 1000

    def shift(x):
        return x + base
    assert pctx.parallelize([1, 2, 3], 3).map(shift).collect() == [
        1001, 1002, 1003]


def test_accumulator_across_process(pctx):
    acc = pctx.accumulator(0)
    pctx.parallelize(range(50), 5).foreach(lambda x: acc.add(1))
    assert acc.value == 50


def test_broadcast_across_process(pctx):
    pctx.start()
    b = pctx.broadcast(list(range(100)))
    got = pctx.parallelize([0, 50, 99], 3).map(lambda i: b.value[i])
    assert got.collect() == [0, 50, 99]


def test_join_across_process(pctx):
    a = pctx.parallelize([("x", 1), ("y", 2)], 2)
    b = pctx.parallelize([("x", "u"), ("z", "w")], 2)
    assert a.join(b, 2).collect() == [("x", (1, "u"))]


def test_sort_across_process(pctx):
    import random
    rng = random.Random(3)
    data = [(rng.randint(0, 100), i) for i in range(200)]
    got = pctx.parallelize(data, 6).sortByKey(numSplits=3).collect()
    assert [k for k, _ in got] == sorted(k for k, _ in data)


def test_task_error_propagates(pctx):
    import pytest
    r = pctx.parallelize(range(4), 2).map(lambda x: 1 // (x - 2))
    with pytest.raises(RuntimeError):
        r.collect()


def test_multi_stage_process(pctx):
    got = dict(
        pctx.parallelize([(i % 5, 1) for i in range(500)], 8)
        .reduceByKey(lambda a, b: a + b, 4)
        .map(lambda kv: (kv[0] % 2, kv[1]))
        .reduceByKey(lambda a, b: a + b, 2)
        .collect())
    assert sum(got.values()) == 500
