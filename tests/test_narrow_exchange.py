"""Dtype narrowing on the all_to_all wire (VERDICT r2 ask #1).

dpark parity requires i64 compute (counting must not wrap at 2**31),
but TPUs have no native i64 datapath — XLA emulates i64 as i32 pairs
and an i64 exchange moves 2x the ICI bytes.  The executor's runtime
min/max guard narrows int64 columns whose valid values fit int32 to
i32 for the collective only, widening right after.  These tests pin
the guard's soundness (parity on edge ranges, per-leaf decisions,
fallback) and the byte win itself.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


def _reduce(ctx, data, parts=8):
    return dict(ctx.parallelize(data, 8)
                .reduceByKey(lambda a, b: a + b, parts).collect())


def _expect(data):
    out = {}
    for k, v in data:
        out[k] = out.get(k, 0) + v
    return out


def test_narrow_halves_wire_bytes(tctx):
    """Small int keys/values ride the wire at i32: exactly half the
    bytes of the i64 exchange for the same data."""
    from dpark_tpu import DparkContext
    data = [(i % 1000, i % 500) for i in range(20000)]
    got = _reduce(tctx, data)
    assert got == _expect(data)
    narrowed = tctx.scheduler.executor.exchange_wire_bytes
    assert narrowed > 0

    import dpark_tpu.conf as conf
    was = conf.NARROW_EXCHANGE
    conf.NARROW_EXCHANGE = False
    try:
        wide_ctx = DparkContext("tpu")
        wide_ctx.start()
        got2 = _reduce(wide_ctx, data)
        assert got2 == _expect(data)
        wide = wide_ctx.scheduler.executor.exchange_wire_bytes
        wide_ctx.stop()
    finally:
        conf.NARROW_EXCHANGE = was
    assert narrowed * 2 == wide, (narrowed, wide)


def test_narrow_is_per_leaf(tctx):
    """Keys beyond i32 keep the i64 wire while small values still
    narrow — the guard decides column by column."""
    data = [(2 ** 40 + (i % 100), 1) for i in range(20000)]
    got = _reduce(tctx, data)
    assert got == _expect(data)
    # key leaf stayed wide (8B) + value narrowed (4B) = 12B per slot
    ex = tctx.scheduler.executor
    assert ex.exchange_wire_bytes % 12 == 0


def test_i32_boundary_values_exact(tctx):
    """Values AT the int32 limits still narrow and stay exact; one past
    the limit falls back to the i64 wire.  Both must agree with the
    local master."""
    lim = 2 ** 31 - 1
    edge = [(1, lim), (1, -lim), (2, lim), (3, -(2 ** 31)), (3, 0)]
    got = _reduce(tctx, edge, parts=4)
    assert got == _expect(edge)

    over = [(1, 2 ** 31), (1, 5), (2, -(2 ** 31) - 1), (2, -5)]
    got2 = _reduce(tctx, over, parts=4)
    assert got2 == _expect(over)


def test_sums_wider_than_i32_still_exact(tctx):
    """Each value fits i32 so the wire narrows, but the reduced sums
    exceed i32 — compute stays i64, so no wrap."""
    data = [(i % 4, 2 ** 30) for i in range(64)]
    got = _reduce(tctx, data, parts=4)
    assert got == _expect(data)
    assert all(v == 16 * 2 ** 30 for v in got.values())


def test_negative_keys_narrow(tctx):
    data = [(-(i % 50) - 1, -i) for i in range(10000)]
    got = _reduce(tctx, data)
    assert got == _expect(data)


def test_narrow_in_sort_and_group(tctx):
    """The no-combine exchanges (sortByKey range exchange, groupByKey)
    run through the same narrowing hook."""
    import random
    rng = random.Random(7)
    data = [(rng.randrange(10000), i) for i in range(20000)]
    got = tctx.parallelize(data, 8).sortByKey(numSplits=8).collect()
    assert got == sorted(data, key=lambda kv: kv[0])

    grouped = dict(tctx.parallelize(data[:4000], 8)
                   .groupByKey(4)
                   .mapValue(sorted).collect())
    expect = {}
    for k, v in data[:4000]:
        expect.setdefault(k, []).append(v)
    assert grouped == {k: sorted(v) for k, v in expect.items()}


def test_ingest_narrows_h2d_wire(tctx):
    """Columnar int64 leaves whose values fit i32 ship to the device
    at i32 (H2D bytes halve); the program widens at entry, so results
    are exact — including at the int32 boundary, where narrowing must
    NOT engage."""
    import numpy as np
    from dpark_tpu import Columns
    from dpark_tpu.backend.tpu import layout
    ex = tctx.scheduler.executor
    i = np.arange(4096, dtype=np.int64)

    # fits i32: the ingested batch's columns must be int32 on device
    pc = tctx.parallelize(Columns(i % 1000, i % 7), 8)
    batch = layout.ingest(ex.mesh, pc._slices,
                          *layout.record_spec((0, 0)), key_leaf=0)
    assert all(str(c.dtype) == "int32" for c in batch.cols), \
        [c.dtype for c in batch.cols]
    got = dict(pc.reduceByKey(lambda a, b: a + b, 8).collect())
    expect = {}
    for k, v in zip((i % 1000).tolist(), (i % 7).tolist()):
        expect[k] = expect.get(k, 0) + v
    assert got == expect

    # beyond i32: values stay int64 on the wire and results are exact
    big = np.int64(2**31) + i               # > int32 max
    pc2 = tctx.parallelize(Columns(i % 50, big), 8)
    batch2 = layout.ingest(ex.mesh, pc2._slices,
                           *layout.record_spec((0, 0)), key_leaf=0)
    assert str(batch2.cols[0].dtype) == "int32"    # keys fit
    assert str(batch2.cols[1].dtype) == "int64"    # values do not
    got2 = dict(pc2.reduceByKey(lambda a, b: a + b, 8).collect())
    expect2 = {}
    for k, v in zip((i % 50).tolist(), big.tolist()):
        expect2[k] = expect2.get(k, 0) + v
    assert got2 == expect2
