"""Component tests: tracker, mutable_dict, hostatus, memory, profile,
nested groupby (reference: per-component unit tests, SURVEY.md section 4).
"""

import os
import time

import pytest


def test_tracker_server_client():
    from dpark_tpu.tracker import TrackerServer, TrackerClient
    srv = TrackerServer(host="127.0.0.1")
    srv.start()
    try:
        c = TrackerClient("127.0.0.1:%d" % srv._server.server_address[1])
        assert c.get("missing") is None
        c.set("k", {"a": 1})
        assert c.get("k") == {"a": 1}
        c.add_item("list", "x")
        c.add_item("list", "y")
        assert c.get("list") == ["x", "y"]
        c.remove_item("list", "x")
        assert c.get("list") == ["y"]
        # second client sees the same data
        c2 = TrackerClient("127.0.0.1:%d" % srv._server.server_address[1])
        assert c2.get("k") == {"a": 1}
        c.close()
        c2.close()
    finally:
        srv.stop()


def test_mutable_dict_local(ctx):
    from dpark_tpu.mutable_dict import MutableDict
    md = MutableDict()
    md.put("init", 100)
    r = ctx.parallelize(range(10), 2)

    def bump(x):
        md.put("task_%d" % x, x * 2)
        return md.get("init") + x

    got = r.map(bump).collect()
    assert got == [100 + i for i in range(10)]
    assert md.get("task_3") == 6
    assert md.get("task_9") == 18


def test_mutable_dict_across_process(pctx):
    from dpark_tpu.mutable_dict import MutableDict
    md = MutableDict()
    md.put("base", 5)
    r = pctx.parallelize(range(8), 4)

    def write(x):
        md.put(x, md.get("base") + x)
        return x

    r.map(write).collect()
    for i in range(8):
        assert md.get(i) == 5 + i


def test_hostatus_blacklist():
    from dpark_tpu.hostatus import TaskHostManager
    m = TaskHostManager()
    now = 1000.0
    for _ in range(5):
        m.task_failed_on("bad-host", now)
    m.task_succeed_on("good-host", now)
    assert m.is_blacklisted("bad-host", now)
    assert not m.is_blacklisted("good-host", now)
    assert m.offer_choice(["bad-host", "good-host"], now) == "good-host"
    # decay: failures age out
    later = now + 600
    assert not m.is_blacklisted("bad-host", later)


def test_memory_rss_and_checker():
    from dpark_tpu.utils.memory import rss_mb, MemoryChecker, MemoryExceeded
    assert rss_mb() > 1.0
    ck = MemoryChecker(limit_mb=0.001, interval=0.01).start()
    time.sleep(0.1)
    with pytest.raises(MemoryExceeded):
        ck.check()
    ck.stop()
    ck2 = MemoryChecker(limit_mb=10**9, interval=0.01).start()
    time.sleep(0.05)
    ck2.check()                        # under limit: no raise
    peak = ck2.stop()
    assert peak > 1.0


def test_memory_kill_and_retry_escalation(pctx):
    """A task over its RSS limit fails, retries escalate the limit, and
    the job eventually succeeds (reference: executor memory kills)."""
    from dpark_tpu.env import env
    env.mem_limit = 1e-3               # absurd 1KB first-try limit

    def hungry(it):
        import time as _t
        blob = [bytes(1 << 20) for _ in range(3)]   # ~3MB
        _t.sleep(0.8)                  # give the sampler time to fire
        from dpark_tpu.utils.memory import maybe_check
        maybe_check()
        return [sum(1 for _ in it) + (len(blob) > 0)]
    try:
        got = pctx.parallelize(range(10), 1).mapPartitions(hungry).collect()
        assert got == [11]
    finally:
        env.mem_limit = None


def test_profile_merge():
    from dpark_tpu.utils.profile import profile_call, MergedProfile

    def work(n):
        return sum(i * i for i in range(n))

    r1, s1 = profile_call(work, 10000)
    r2, s2 = profile_call(work, 20000)
    assert r1 == sum(i * i for i in range(10000))
    m = MergedProfile()
    m.add(s1)
    m.add(s2)
    out = m.summary(5)
    assert "work" in out


def test_nested_groupby_spill(tmp_path):
    from dpark_tpu.utils.nested_groupby import group_by_nested
    data = [("k%d" % (i % 3), i) for i in range(1000)]
    groups = dict(group_by_nested(iter(data), lambda kv: kv[0],
                                  max_in_memory=50))
    assert set(groups) == {"k0", "k1", "k2"}
    for k, g in groups.items():
        vals = [v for _, v in g]
        assert len(vals) == len(g)
        expect = [i for i in range(1000) if "k%d" % (i % 3) == k]
        assert [v for _, v in g] == expect      # re-iterable
        g.close()


def test_mutable_dict_many_tasks_same_key(pctx):
    """Every task of a job writes the same pre-existing key; the final
    value must come from one of them, never the stale original."""
    from dpark_tpu.mutable_dict import MutableDict
    md = MutableDict()
    md.put("base", -1)

    def write(x):
        md.put("base", 1000 + x)
        return x

    pctx.parallelize(range(8), 8).map(write).collect()
    assert md.get("base") in {1000 + i for i in range(8)}


def test_mutable_dict_driver_write_between_jobs(pctx):
    from dpark_tpu.mutable_dict import MutableDict
    md = MutableDict()
    md.put("a", 1)
    r = pctx.parallelize([0], 1)
    assert r.map(lambda _: md.get("a")).collect() == [1]
    md.put("b", 2)                     # driver write AFTER first job
    assert r.map(lambda _: md.get("b")).collect() == [2]


def test_tracker_mutation_dedup():
    from dpark_tpu.tracker import (TrackerServer, TrackerClient,
                                   AddItemMessage)
    srv = TrackerServer(host="127.0.0.1")
    srv.start()
    try:
        c = TrackerClient("127.0.0.1:%d" % srv._server.server_address[1])
        msg = AddItemMessage("k", "v")
        c.call(msg)
        c.call(msg)                    # simulated retry of the same message
        assert c.get("k") == ["v"]
        c.close()
    finally:
        srv.stop()


def test_file_manager_walk_and_locations(tmp_path):
    from dpark_tpu import file_manager as fm
    (tmp_path / "a.txt").write_text("x")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b.txt").write_text("yy")
    files = dict(fm.walk(str(tmp_path)))
    assert set(os.path.basename(p) for p in files) == {"a.txt", "b.txt"}
    assert fm.file_size(str(tmp_path / "a.txt")) == 1
    assert fm.locations(str(tmp_path / "a.txt"))  # non-empty host list
    assert fm.chunks_of(str(tmp_path / "sub" / "b.txt")) == [(0, 2)]


def test_file_manager_scheme_registry(tmp_path):
    from dpark_tpu import file_manager as fm
    import pytest as _pytest
    with _pytest.raises(ValueError):
        fm.get_filesystem("nosuch://x")
    fs, p = fm.get_filesystem("file://" + str(tmp_path))
    assert p == str(tmp_path)


def test_web_ui_serves_history(ctx):
    import json
    import urllib.request
    from dpark_tpu.web import start_ui
    ctx.parallelize(range(10), 2).count()
    server, url = start_ui(ctx.scheduler)
    try:
        jobs = json.loads(urllib.request.urlopen(url + "api/jobs",
                                                 timeout=5).read())
        assert jobs and jobs[-1]["state"] == "done"
        assert jobs[-1]["finished"] == 2
        html = urllib.request.urlopen(url, timeout=5).read()
        assert b"dpark_tpu" in html
    finally:
        server.shutdown()


def test_stage_info_records(ctx):
    """Per-stage observability (SURVEY.md 5.1): the job record carries
    stage timings; the web UI surfaces them."""
    import json
    import urllib.request
    from dpark_tpu.web import start_ui
    ctx.parallelize([(i % 3, 1) for i in range(50)], 4) \
       .reduceByKey(lambda a, b: a + b, 2).collect()
    rec = ctx.scheduler.history[-1]
    infos = rec["stage_info"]
    assert len(infos) == 2                    # map + reduce stages
    assert any(i["shuffle"] for i in infos)
    assert all(i["seconds"] is not None for i in infos)
    # DAG edges: the result stage names the map stage as its parent
    by_id = {i["id"]: i for i in infos}
    child = [i for i in infos if i["parents"]][0]
    assert by_id[child["parents"][0]]["shuffle"]
    server, url = start_ui(ctx.scheduler)
    try:
        jobs = json.loads(urllib.request.urlopen(url + "api/jobs",
                                                 timeout=5).read())
        assert jobs[-1]["stage_info"][0]["parts"] >= 1
    finally:
        server.shutdown()


@pytest.mark.mesh
def test_stage_info_array_kind():
    """On the tpu master the array path annotates kind/run time."""
    from dpark_tpu import DparkContext
    tctx = DparkContext("tpu")
    tctx.start()
    try:
        tctx.parallelize([(i % 5, 1) for i in range(200)], 8) \
            .reduceByKey(lambda a, b: a + b, 8).collect()
        infos = tctx.scheduler.history[-1]["stage_info"]
        kinds = {i.get("kind") for i in infos}
        assert "array" in kinds, infos
        arr = [i for i in infos if i.get("kind") == "array"][0]
        assert arr["run_seconds"] >= 0
        assert any("hbm_bytes" in i for i in infos)
    finally:
        tctx.stop()


def test_distributed_init_single():
    from dpark_tpu.distributed import init
    pid, n = init(num_processes=1, process_id=0)
    assert (pid, n) == (0, 1)


def test_drun_tool(tmp_path):
    import subprocess, sys
    out = subprocess.run(
        [sys.executable, "tools/drun", "-n", "3",
         sys.executable, "-c",
         "import os; print('slot', os.environ['DRUN_SLOT'])"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0
    assert sorted(out.stdout.split()) .count("slot") == 3


def test_mrun_tool():
    import subprocess, sys
    out = subprocess.run(
        [sys.executable, "tools/mrun", "-n", "2",
         sys.executable, "-c",
         "import os; print('rank', os.environ['MRUN_RANK'])"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0
    assert "[rank 0]" in out.stdout and "[rank 1]" in out.stdout


def test_textfile_missing_path_raises(ctx):
    with pytest.raises(FileNotFoundError):
        ctx.textFile("/no/such/file_xyz.txt").count()


def test_walk_requalifies_scheme(tmp_path):
    from dpark_tpu import file_manager as fm

    class FakeFS(fm.LocalFileSystem):
        scheme = "fake"
    fm.register_filesystem("fake", FakeFS())
    (tmp_path / "f.txt").write_text("hello\n")
    files = list(fm.walk("fake://" + str(tmp_path)))
    assert files and files[0][0].startswith("fake://")
    # and per-file calls route back through the fake scheme
    assert fm.file_size(files[0][0]) == 6


def test_take_job_recorded_as_partial(ctx):
    ctx.parallelize(range(100), 10).take(3)
    states = [j["state"] for j in ctx.scheduler.history]
    assert "aborted" not in states
    ctx.parallelize(range(100), 10).collect()
    assert ctx.scheduler.history[-1]["state"] == "done"


def test_web_ui_tasks_and_profile(ctx):
    """r5 (VERDICT r4 weak #5): per-task drill-down records in the
    stage info and the /api/profile endpoint."""
    import json
    import urllib.request
    from dpark_tpu.web import start_ui
    ctx.parallelize(range(20), 4).map(lambda x: x * 2).collect()
    rec = ctx.scheduler.history[-1]
    tasks = rec["stage_info"][0].get("tasks")
    assert tasks and len(tasks) == 4
    assert {t["p"] for t in tasks} == {0, 1, 2, 3}
    assert all(t["ok"] and t["s"] >= 0 for t in tasks)
    server, url = start_ui(ctx.scheduler)
    try:
        jobs = json.loads(urllib.request.urlopen(url + "api/jobs",
                                                 timeout=5).read())
        assert jobs[-1]["stage_info"][0]["tasks"]
        prof = urllib.request.urlopen(url + "api/profile",
                                      timeout=5).read()
        assert b"profile" in prof         # placeholder without --profile
    finally:
        server.shutdown()
