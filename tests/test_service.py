"""Resident executor service (ISSUE 9): one mesh, many concurrent
jobs.

The suite proves the three contracts the job server makes:

* PARITY — two drivers submitting interleaved reduceByKey/join DAGs
  produce bit-identical results vs serial execution, including under
  an injected-fault chaos cell and a device OOM-ladder cell.
* ISOLATION — per-job record counters (recovery, decodes, adapt,
  program-cache deltas) never cross-contaminate between concurrent
  jobs.
* AMORTIZATION — a warm re-submission of an identical DAG compiles
  NOTHING (asserted from the bounded program cache's counters), and a
  completed job's HBM buckets spill to disk bucket files under budget
  pressure instead of costing the next reader a lineage recompute.

Device tests run on a 2-device sliced mesh ("tpu:2") so the suite
works on small containers.
"""

import threading
import time

import numpy as np
import pytest

from dpark_tpu import DparkContext, conf, faults, service
from dpark_tpu.backend.tpu.executor import _ProgramCache
from dpark_tpu.service import JobServer, _JobState


@pytest.fixture(autouse=True)
def _clean_service():
    """Every test starts and ends without the process-global server,
    without a chaos plane, and with stock service knobs."""
    service.shutdown()
    faults.configure(None)
    yield
    service.shutdown()
    faults.configure(None)


@pytest.fixture()
def sctx():
    """A context attached to an in-process service over the LOCAL
    master (the golden-model inner scheduler)."""
    c = DparkContext("service:local")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def stctx():
    """A context attached to an in-process service over a 2-device
    tpu master — concurrent jobs share one mesh + executor."""
    c = DparkContext("service:tpu:2")
    c.start()
    yield c
    c.stop()


def _add(a, b):
    return a + b


def _reduce_job(ctx, n, k, numSplits=4, width=3):
    data = [(i % k, 1) for i in range(n)]
    return dict(ctx.parallelize(data, numSplits)
                .reduceByKey(_add, width).collect())


def _join_job(ctx, n):
    a = ctx.parallelize([(i % 11, i) for i in range(n)], 3)
    b = ctx.parallelize([(i % 11, i * 2) for i in range(0, n, 2)], 3)
    return sorted(a.join(b, 3).collect())


def _expected_reduce(n, k):
    return {i: n // k + (1 if i < n % k else 0) for i in range(k)}


# ---------------------------------------------------------------------------
# the bounded program cache (satellite)
# ---------------------------------------------------------------------------

def test_program_cache_lru_and_counters():
    pc = _ProgramCache(cap=2)
    assert ("a" in pc) is False          # miss
    pc["a"] = 1
    pc["b"] = 2
    assert "a" in pc and pc["a"] == 1    # hit + LRU touch
    pc["c"] = 3                          # evicts b (a was touched)
    s = pc.stats()
    assert s["evictions"] == 1 and s["entries"] == 2
    assert "b" not in pc and "a" in pc and "c" in pc
    assert s["hits"] >= 1 and s["misses"] >= 1


def test_program_cache_unbounded_when_zero():
    pc = _ProgramCache(cap=0)
    for i in range(100):
        pc[i] = i
    assert len(pc) == 100 and pc.stats()["evictions"] == 0


# ---------------------------------------------------------------------------
# fair dispatcher mechanics
# ---------------------------------------------------------------------------

def test_weighted_round_robin_order():
    srv = JobServer("local")
    heavy = _JobState(2, {"state": "running"})
    light = _JobState(1, {"state": "running"})
    srv._jobs = {1: heavy, 2: light}
    srv._rr = [1, 2]
    for i in range(60):
        heavy.queue.append(("h", i))
        light.queue.append(("l", i))
    got = [srv._next_work()[0] for _ in range(30)]
    # weight 2 job gets two turns per cycle, weight 1 gets one — and
    # the light job is never starved
    assert got.count("h") == 20 and got.count("l") == 10
    assert "l" in got[:3]


def test_admission_control_blocks_and_refuses(sctx):
    srv = sctx.scheduler.server
    srv.max_jobs = 1
    srv.queue_max = 1
    release = threading.Event()
    started = threading.Event()

    def slow(v):
        started.set()
        release.wait(timeout=30)
        return v

    out = {}

    def run_slow():
        out["slow"] = dict(
            sctx.parallelize([(1, 1)], 1).mapValue(slow).collect())

    t1 = threading.Thread(target=run_slow)
    t1.start()
    assert started.wait(timeout=30)
    # job 2 queues behind the admission cap
    t2 = threading.Thread(
        target=lambda: out.update(q=_reduce_job(sctx, 50, 5)))
    t2.start()
    deadline = time.time() + 10
    while time.time() < deadline \
            and srv.service_stats()["jobs_queued"] < 1:
        time.sleep(0.01)
    assert srv.service_stats()["jobs_queued"] == 1
    # job 3 is REFUSED: the bounded queue is full
    gen = srv.submit(sctx.parallelize([1], 1), list)
    with pytest.raises(RuntimeError, match="admission queue full"):
        next(gen)
    release.set()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert out["slow"] == {1: 1}
    assert out["q"] == _expected_reduce(50, 5)


def test_nested_submission_bypasses_admission(sctx):
    """A driver holding an admission slot must be able to submit a
    nested job from the same thread (sortByKey samples, collects
    inside an iterate loop) — at max_jobs=1 this would otherwise be a
    self-deadlock."""
    srv = sctx.scheduler.server
    srv.max_jobs = 1
    seen = []
    for x in sctx.parallelize(list(range(20)), 2).iterate():
        if not seen:
            # nested job while the outer generator holds the only slot
            seen.append(_reduce_job(sctx, 100, 4))
    assert seen[0] == _expected_reduce(100, 4)
    # sortByKey's bounds-sample job nests the same way
    got = sctx.parallelize([(i % 9, i) for i in range(300)], 4) \
        .sortByKey(numSplits=3).collect()
    assert got == sorted([(i % 9, i) for i in range(300)])


# ---------------------------------------------------------------------------
# concurrent-jobs parity (local master cell)
# ---------------------------------------------------------------------------

def test_two_drivers_interleaved_parity_local(sctx):
    serial_a = _reduce_job(sctx, 3000, 7)
    serial_b = _join_job(sctx, 600)
    got = {}

    def driver_a():
        for _ in range(3):
            got.setdefault("a", []).append(_reduce_job(sctx, 3000, 7))

    def driver_b():
        for _ in range(3):
            got.setdefault("b", []).append(_join_job(sctx, 600))

    ts = [threading.Thread(target=driver_a),
          threading.Thread(target=driver_b)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert all(r == serial_a for r in got["a"])
    assert all(r == serial_b for r in got["b"])


def test_chaos_cell_concurrent_parity_and_isolation(sctx):
    """The ISSUE 9 chaos cell: interleaved jobs under
    shuffle.fetch:p=0.2 stay bit-identical, and each job's recovery
    counters land on ITS record (stage_info sets are disjoint)."""
    # `times` bounds total firings: two jobs drawing from one seeded
    # pattern interleave nondeterministically, and unbounded p=0.2
    # can push one job past MAX_STAGE_FAILURES — the cell grades
    # parity under faults, not infinite-fault survival
    faults.configure("shuffle.fetch:p=0.2,seed=7,times=4")
    got = {}
    ts = [threading.Thread(
              target=lambda: got.update(a=_reduce_job(sctx, 2000, 5))),
          threading.Thread(
              target=lambda: got.update(b=_join_job(sctx, 400)))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    faults.configure(None)
    assert got["a"] == _expected_reduce(2000, 5)
    assert got["b"] == _join_job(sctx, 400)
    hist = [r for r in sctx.scheduler.history if r.get("service")]
    recs = hist[:2]
    assert len(recs) == 2 and recs[0]["id"] != recs[1]["id"]
    stages = [set(st["id"] for st in r["stage_info"]) for r in recs]
    assert not (stages[0] & stages[1]), "stage records leaked between jobs"
    # the injected faults actually fired and recovery ran somewhere
    assert faults.stats() == {} or True     # plane cleared above
    total_recovery = sum(r.get("resubmits", 0) + r.get("retries", 0)
                         + r.get("recomputes", 0) for r in recs)
    assert total_recovery >= 1, recs


# ---------------------------------------------------------------------------
# concurrent-jobs parity (device cells)
# ---------------------------------------------------------------------------

def _device_reduce(ctx, n, k, width=2):
    from dpark_tpu import Columns
    i = np.arange(n, dtype=np.int64)
    return dict(ctx.parallelize(Columns(i % k, np.ones(n, np.int64)),
                                2)
                .reduceByKey(_add, width).collect())


def test_two_drivers_parity_tpu(stctx):
    serial_a = _device_reduce(stctx, 30000, 13)
    serial_b = _join_job(stctx, 500)
    got = {}
    ts = [threading.Thread(target=lambda: got.update(
              a=_device_reduce(stctx, 30000, 13))),
          threading.Thread(target=lambda: got.update(
              b=_join_job(stctx, 500)))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert got["a"] == serial_a == {i: 30000 // 13 + (1 if i < 30000 % 13 else 0)
                                    for i in range(13)}
    assert got["b"] == serial_b


def test_oom_ladder_cell_concurrent(stctx):
    """Device OOM-ladder cell: one job trips the emulated HBM ceiling
    (walks the halving ladder) while another runs concurrently — both
    stay bit-identical, and the degrade_reason lands on the OOM'd
    job's record only."""
    old_ceil = conf.EMULATED_WAVE_OOM_ROWS
    old_rows = conf.STREAM_CHUNK_ROWS
    old_fallback = conf._STREAM_CHUNK_ROWS_FALLBACK
    got = {}
    try:
        # force the wave stream at toy sizes (the adapt bench recipe):
        # auto budget = 6000 rows/device > the 4000-row emulated
        # ceiling, so the first wave OOMs and the ladder halves to
        # 3000 — which fits
        conf.STREAM_CHUNK_ROWS = "auto"
        conf._STREAM_CHUNK_ROWS_FALLBACK = 6000
        conf.EMULATED_WAVE_OOM_ROWS = 4000

        def oom_job():
            got["a"] = _device_reduce(stctx, 30000, 11)

        def clean_job():
            got["b"] = _reduce_job(stctx, 900, 3)

        ts = [threading.Thread(target=oom_job),
              threading.Thread(target=clean_job)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    finally:
        conf.EMULATED_WAVE_OOM_ROWS = old_ceil
        conf.STREAM_CHUNK_ROWS = old_rows
        conf._STREAM_CHUNK_ROWS_FALLBACK = old_fallback
    assert got["a"] == {i: 30000 // 11 + (1 if i < 30000 % 11 else 0)
                        for i in range(11)}
    assert got["b"] == _expected_reduce(900, 3)
    hist = [r for r in stctx.scheduler.history if r.get("service")]
    degraded = [r for r in hist
                for st in r.get("stage_info", ())
                if st.get("degrade_reason")]
    # the degrade landed on the device job's record, and the clean
    # python job's record carries none
    by_id = {}
    for r in hist:
        for st in r.get("stage_info", ()):
            if st.get("degrade_reason"):
                by_id.setdefault(r["id"], []).append(
                    st["degrade_reason"])
    clean = [r for r in hist if r["parts"] == 3 and r["id"] not in by_id]
    assert degraded, hist
    assert clean, hist


# ---------------------------------------------------------------------------
# amortized compile: warm submission hits the cache end to end
# ---------------------------------------------------------------------------

def test_warm_submission_compiles_nothing(stctx):
    sched = stctx.scheduler
    ex = sched.executor
    out1 = _device_reduce(stctx, 20000, 13)
    pc1 = ex.program_cache_stats()
    out2 = _device_reduce(stctx, 20000, 13)
    pc2 = ex.program_cache_stats()
    assert out1 == out2
    assert pc2["misses"] == pc1["misses"], \
        "warm submission re-compiled a stage program"
    assert pc2["hits"] > pc1["hits"]
    rec = [r for r in sched.history if r.get("service")][-1]
    assert rec["program_cache"]["misses"] == 0
    assert rec["program_cache"]["hits"] >= 1
    assert rec.get("first_wave_ms") is not None
    assert rec.get("queue_wait_ms") is not None
    assert rec.get("client")


# ---------------------------------------------------------------------------
# per-job counter isolation (decodes)
# ---------------------------------------------------------------------------

def test_decode_counters_do_not_cross_contaminate(stctx):
    """Job A (host path, coded disk shuffles, injected fetch faults)
    decodes; job B (device path, no fetches) runs concurrently — B's
    record must show ZERO decode activity even though the
    process-global counters moved while it ran."""
    from dpark_tpu import coding
    coding.configure("rs(4,2)")
    faults.configure("shuffle.fetch:p=0.3,seed=7")
    got = {}
    try:
        def job_a():
            # groupByKey().mapValue(set) declines the array path:
            # object map tasks write coded DISK containers, reduces
            # fetch them under injected faults -> repairs
            data = [(i % 7, i % 5) for i in range(2000)]
            got["a"] = dict(
                stctx.parallelize(data, 4).groupByKey(4)
                .mapValue(lambda vs: len(set(vs))).collect())

        def job_b():
            got["b"] = _device_reduce(stctx, 20000, 7)

        ts = [threading.Thread(target=job_a),
              threading.Thread(target=job_b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    finally:
        faults.configure(None)
        coding.configure(None)
    assert got["a"] == {k: 5 for k in range(7)}
    assert got["b"] == {i: 20000 // 7 + (1 if i < 20000 % 7 else 0)
                        for i in range(7)}
    hist = [r for r in stctx.scheduler.history if r.get("service")]
    rec_a = [r for r in hist if r["parts"] == 4][0]
    rec_b = [r for r in hist if r["parts"] == 2][0]
    da = rec_a.get("decodes", {})
    db = rec_b.get("decodes", {})
    assert da.get("repair", 0) > 0, (da, rec_a)
    assert not any(v for k, v in db.items() if k != "mode"), \
        "device job's record absorbed another job's decode counters"
    # coded mode absorbed the faults: no lineage recovery anywhere
    assert rec_a.get("resubmits", 0) == 0, rec_a


# ---------------------------------------------------------------------------
# HBM eviction spills to disk instead of recomputing (satellite)
# ---------------------------------------------------------------------------

def test_completed_job_buckets_spill_to_disk_not_recompute():
    import glob
    import os
    from dpark_tpu.env import env
    ctx = DparkContext("tpu:2")
    ctx.start()
    try:
        r1 = ctx.parallelize([(i % 4, 1) for i in range(4000)], 2) \
                .reduceByKey(_add, 2)
        assert dict(r1.collect()) == {k: 1000 for k in range(4)}
        old = conf.SHUFFLE_HBM_BUDGET
        conf.SHUFFLE_HBM_BUDGET = 1
        try:
            r2 = ctx.parallelize([(i % 3, 2) for i in range(900)], 2) \
                    .reduceByKey(_add, 2)
            assert dict(r2.collect()) == {k: 600 for k in range(3)}
        finally:
            conf.SHUFFLE_HBM_BUDGET = old
        files = glob.glob(os.path.join(env.workdir, "shuffle",
                                       "*", "*", "*"))
        assert files, "eviction wrote no disk buckets"
        # the re-read consumes the DISK buckets: zero lineage recovery
        assert dict(r1.collect()) == {k: 1000 for k in range(4)}
        rec = ctx.scheduler.history[-1]
        assert rec.get("resubmits", 0) == 0, rec
        assert rec.get("recomputes", 0) == 0, rec
    finally:
        ctx.stop()


# ---------------------------------------------------------------------------
# seams: off-by-default, env attach, remote transport
# ---------------------------------------------------------------------------

def test_service_off_is_inert(ctx):
    """With DPARK_SERVICE unset, the scheduler runs exactly the
    pre-service path: no service attached, no service fields on the
    record."""
    assert ctx.scheduler is None or True
    got = _reduce_job(ctx, 500, 5)
    assert got == _expected_reduce(500, 5)
    sched = ctx.scheduler
    assert sched._service is None
    rec = sched.history[-1]
    for key in ("service", "client", "queue_wait_ms", "_sids",
                "_t_submit"):
        assert key not in rec, key


def test_dpark_service_env_attaches(monkeypatch):
    monkeypatch.setattr(conf, "DPARK_SERVICE", "local")
    ctx = DparkContext("local")
    ctx.start()
    try:
        from dpark_tpu.service import ClientScheduler
        assert isinstance(ctx.scheduler, ClientScheduler)
        assert _reduce_job(ctx, 300, 3) == _expected_reduce(300, 3)
        rec = ctx.scheduler.history[-1]
        assert rec.get("service") and rec.get("client")
    finally:
        ctx.stop()


def test_remote_two_clients_share_one_server():
    framed = service.serve("127.0.0.1:0", master="local")
    try:
        addr = "%s:%d" % framed.bind_address
        c1 = service.ServiceClient(addr, client="tenant-a")
        c2 = service.ServiceClient(addr, client="tenant-b")

        def job_fn(ctx):
            return dict(ctx.parallelize(
                [(i % 5, 1) for i in range(1000)], 4)
                .reduceByKey(_add, 3).collect())

        got = {}
        ts = [threading.Thread(
                  target=lambda: got.update(a=c1.run(job_fn))),
              threading.Thread(
                  target=lambda: got.update(b=c2.run(job_fn)))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        expect = _expected_reduce(1000, 5)
        assert got["a"] == expect and got["b"] == expect
        stats = c1.stats()
        assert stats["master"] == "local"
        srv = service.get_server()
        clients = {r.get("client")
                   for r in srv.scheduler.history
                   if r.get("service")}
        assert {"remote:tenant-a", "remote:tenant-b"} <= clients
    finally:
        framed.stop()


# ---------------------------------------------------------------------------
# graceful degradation: the drain endpoint (ISSUE 20)
# ---------------------------------------------------------------------------

def _drain_probe_job(ctx):
    return sorted(ctx.parallelize([(i % 3, 1) for i in range(30)], 2)
                  .reduceByKey(_add, 2).collect())


def test_remote_drain_stops_admission_and_flushes(tmp_path):
    """ServiceClient.drain: the server stops admission, finishes
    in-flight work, flushes the crash journal, and refuses new jobs
    until undrained."""
    from dpark_tpu import journal
    journal.configure(mode="on", journal_dir=str(tmp_path / "jnl"))
    try:
        framed = service.serve("127.0.0.1:0", master="local")
        try:
            addr = "%s:%d" % framed.bind_address
            c = service.ServiceClient(addr, client="drainer")
            assert c.run(_drain_probe_job) == [(0, 10), (1, 10),
                                               (2, 10)]
            summary = c.drain(timeout_s=10)
            assert summary["drained"] is True
            assert summary["journal_flushed"] is True
            srv = service.get_server()
            assert srv.service_stats()["draining"] is True
            with pytest.raises(Exception) as e:
                c.run(_drain_probe_job)
            assert "draining" in str(e.value)
            # drain is idempotent
            again = c.drain(timeout_s=1)
            assert again["was_draining"] is True
            srv.undrain()
            assert c.run(_drain_probe_job) == [(0, 10), (1, 10),
                                               (2, 10)]
        finally:
            framed.stop()
    finally:
        journal.configure(mode="off")
