"""Device-native Pregel (bagel.run_pregel): every test asserts the tpu
master's fused-superstep output == the vectorized host golden model (and,
for PageRank, == the reference object-Bagel formulation)."""

import numpy as np
import pytest

from dpark_tpu.bagel import _pregel_host, run_pregel

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


def _ring_graph(n):
    ids = np.arange(n, dtype=np.int64)
    src = np.repeat(ids, 2)
    dst = np.stack([(ids + 1) % n, (ids * 7 + 3) % n], 1).reshape(-1)
    return ids, src, dst


def _pagerank_fns(n, damping=0.85, steps=20):
    def compute(value, msg, has_msg, active, agg, superstep):
        is0 = superstep == 0
        new = is0 * value + (1 - is0) * ((1 - damping) / n
                                         + damping * msg)
        return new, superstep < steps

    def send(src_value, edge_value, src_degree):
        return src_value / src_degree
    return compute, send


def test_pagerank_device_matches_host(tctx):
    n = 64
    ids, src, dst = _ring_graph(n)
    values = np.full(n, 1.0 / n)
    compute, send = _pagerank_fns(n)
    gids, granks, _ = run_pregel(tctx, ids, values, (src, dst),
                                 compute, send, combine="add")
    assert tctx.scheduler._pregel_device_used
    hids, hranks, _ = _pregel_host(ids, values, (src, dst), compute,
                                   send, "add", None, None, None, None,
                                   80)
    assert np.array_equal(gids, hids)
    assert np.allclose(granks, hranks)
    assert abs(float(np.sum(granks)) - 1.0) < 1e-6


def test_pagerank_matches_object_bagel(tctx):
    """The vectorized contract reproduces the reference object-Bagel
    numbers on the same graph."""
    import operator
    from dpark_tpu import DparkContext
    from dpark_tpu.bagel import Bagel, BasicCombiner, Edge, Message, \
        Vertex
    n = 32
    ids, src, dst = _ring_graph(n)
    compute, send = _pagerank_fns(n)
    _, granks, _ = run_pregel(tctx, ids, np.full(n, 1.0 / n),
                              (src, dst), compute, send, combine="add")

    class ObjPR:
        def __call__(self, vert, msg_sum, agg, superstep):
            if superstep == 0:
                value = vert.value
            else:
                value = (1 - 0.85) / n + 0.85 * (msg_sum or 0.0)
            active = superstep < 20
            v = Vertex(vert.id, value, vert.outEdges, active)
            if active and vert.outEdges:
                share = value / len(vert.outEdges)
                return (v, [Message(e.target_id, share)
                            for e in vert.outEdges])
            return (v, [])

    lctx = DparkContext("local")
    verts = lctx.parallelize(
        [(int(i), Vertex(int(i), 1.0 / n,
                         [Edge(int(t)) for t in dst[src == i]]))
         for i in ids], 4)
    msgs = lctx.parallelize([], 4)
    final = Bagel.run(lctx, verts, msgs, ObjPR(),
                      combiner=BasicCombiner(operator.add))
    obj = dict((vid, v.value) for vid, v in final.collect())
    lctx.stop()
    assert np.allclose(granks, [obj[int(i)] for i in ids])


def test_sssp_min_combine_initial_messages(tctx):
    """Single-source shortest paths: min monoid, weighted edges, initial
    message wakes the source, vertices halt when no improvement."""
    rng = np.random.RandomState(7)
    n = 50
    ids = np.arange(n, dtype=np.int64) * 3 + 1      # non-contiguous ids
    ne = 200
    src = ids[rng.randint(0, n, ne)]
    dst = ids[rng.randint(0, n, ne)]
    w = rng.randint(1, 10, ne).astype(np.float64)
    dist0 = np.full(n, np.inf)

    def compute(dist, msg, has_msg, active, agg, superstep):
        import jax.numpy as jnp           # works on np arrays and tracers
        new = jnp.minimum(dist, msg)
        return new, new < dist

    def send(d, w_edge, deg):
        return d + w_edge

    init = (np.array([ids[0]]), np.array([0.0]))
    gids, gdist, _ = run_pregel(tctx, ids, dist0, (src, dst), compute,
                                send, combine="min", edge_values=w,
                                initial_messages=init)
    assert tctx.scheduler._pregel_device_used
    hids, hdist, _ = _pregel_host(ids, dist0, (src, dst), compute, send,
                                  "min", w, None, init, None, 80)
    assert np.array_equal(gids, hids)
    assert np.allclose(gdist, hdist, equal_nan=True)

    # independent Bellman-Ford check
    ref = {int(i): np.inf for i in ids}
    ref[int(ids[0])] = 0.0
    for _ in range(n):
        for s, d, ww in zip(src, dst, w):
            if ref[int(s)] + ww < ref[int(d)]:
                ref[int(d)] = ref[int(s)] + ww
    assert np.allclose(gdist, [ref[int(i)] for i in gids],
                       equal_nan=True)


def test_aggregator_psum(tctx):
    """aggregated = global reduce over the PRE-compute state, visible to
    compute the same superstep."""
    n = 40
    ids = np.arange(n, dtype=np.int64)
    src = ids
    dst = (ids + 1) % n
    values = np.arange(n, dtype=np.float64)

    def compute(value, msg, has_msg, active, agg, superstep):
        return value * 0 + agg, superstep < 1      # value' = global sum

    def send(v, e, deg):
        return v * 0.0

    agg = (lambda v: v, "add")
    gids, gvals, _ = run_pregel(tctx, ids, values, (src, dst), compute,
                                send, combine="add", aggregator=agg,
                                max_superstep=1)
    assert tctx.scheduler._pregel_device_used
    assert np.allclose(gvals, np.sum(values))
    hids, hvals, _ = _pregel_host(ids, values, (src, dst), compute,
                                  send, "add", None, None, None, agg, 1)
    assert np.allclose(gvals, hvals)


def test_tuple_values_and_messages(tctx):
    """Tuple-leaf vertex state and messages; monoid combines per leaf."""
    n = 24
    ids = np.arange(n, dtype=np.int64)
    src = np.repeat(ids, 2)
    dst = np.stack([(ids + 1) % n, (ids + 5) % n], 1).reshape(-1)
    v0 = (np.ones(n), np.arange(n, dtype=np.int64))

    def compute(values, msg, has_msg, active, agg, superstep):
        a, b = values
        ma, mb = msg
        return (a + ma, b + mb), superstep < 3

    def send(values, e, deg):
        a, b = values
        return (a * 0.5, b)

    gids, gvals, _ = run_pregel(tctx, ids, v0, (src, dst), compute,
                                send, combine="add")
    assert tctx.scheduler._pregel_device_used
    hids, hvals, _ = _pregel_host(ids, v0, (src, dst), compute, send,
                                  "add", None, None, None, None, 80)
    assert np.array_equal(gids, hids)
    for g, h in zip(gvals, hvals):
        assert np.allclose(g, h)


def test_all_inactive_halts_immediately(tctx):
    n = 8
    ids = np.arange(n, dtype=np.int64)

    def compute(value, msg, has_msg, active, agg, superstep):
        return value, value < 0          # never active

    def send(v, e, deg):
        return v

    gids, gvals, gact = run_pregel(
        tctx, ids, np.ones(n), (ids, (ids + 1) % n), compute, send)
    assert not gact.any()
    assert np.allclose(gvals, 1.0)


def test_messages_to_unknown_ids_dropped(tctx):
    """Parity with the object path: mail to ids with no vertex vanishes."""
    n = 8
    ids = np.arange(n, dtype=np.int64)
    src = ids
    dst = np.where(ids < 4, ids + 1, 1000 + ids)    # half point nowhere

    def compute(value, msg, has_msg, active, agg, superstep):
        return value + msg, superstep < 2

    def send(v, e, deg):
        return v * 0 + 1.0

    gids, gvals, _ = run_pregel(tctx, ids, np.zeros(n), (src, dst),
                                compute, send, combine="add")
    hids, hvals, _ = _pregel_host(ids, np.zeros(n), (src, dst), compute,
                                  send, "add", None, None, None, None,
                                  80)
    assert np.allclose(gvals, hvals)


def test_input_errors_surface_not_fallback(tctx):
    """Invalid input raises PregelInputError on the tpu master instead
    of silently degrading to the host path with wrong results."""
    from dpark_tpu.bagel import PregelInputError
    ids = np.arange(8, dtype=np.int64)

    def compute(v, m, h, a, agg, s):
        return v, s < 1

    def send(v, e, deg):
        return v

    with pytest.raises(PregelInputError):        # duplicate ids
        run_pregel(tctx, np.zeros(4, np.int64), np.ones(4),
                   (np.zeros(1, np.int64), np.zeros(1, np.int64)),
                   compute, send)
    with pytest.raises(PregelInputError):        # unknown edge source
        run_pregel(tctx, ids, np.ones(8),
                   (np.array([99]), np.array([0])), compute, send)
    with pytest.raises(PregelInputError):        # msg leaf mismatch
        run_pregel(tctx, ids, np.ones(8), (ids, (ids + 1) % 8),
                   compute, send,
                   initial_messages=(np.array([0]),
                                     (np.ones(1), np.ones(1))))


def test_empty_graph_and_no_edges(tctx):
    def compute(v, m, h, a, agg, s):
        return v, s < 1

    def send(v, e, deg):
        return v

    gids, gvals, gact = run_pregel(
        tctx, np.zeros(0, np.int64), np.zeros(0),
        (np.zeros(0, np.int64), np.zeros(0, np.int64)), compute, send)
    assert gids.size == 0 and gvals.size == 0 and gact.size == 0

    # vertices but no edges: one superstep, no messages, halt
    ids = np.arange(5, dtype=np.int64)
    gids, gvals, _ = run_pregel(
        tctx, ids, np.ones(5),
        (np.zeros(0, np.int64), np.zeros(0, np.int64)), compute, send)
    hids, hvals, _ = _pregel_host(
        ids, np.ones(5),
        (np.zeros(0, np.int64), np.zeros(0, np.int64)), compute, send,
        "add", None, None, None, None, 80)
    assert np.array_equal(gids, hids)
    assert np.allclose(gvals, hvals)


def test_pregel_fuzz_host_vs_device(tctx):
    """Random graphs / monoids: device == host on every superstep path."""
    for seed, combine in [(1, "add"), (2, "min"), (3, "max")]:
        rng = np.random.RandomState(seed)
        n = rng.randint(10, 60)
        ids = np.sort(rng.choice(10000, n, replace=False)).astype(
            np.int64)
        ne = rng.randint(n, 4 * n)
        src = ids[rng.randint(0, n, ne)]
        dst = ids[rng.randint(0, n, ne)]
        w = rng.randint(0, 5, ne).astype(np.float64)
        v0 = rng.randint(0, 100, n).astype(np.float64)
        steps = int(rng.randint(1, 5))

        def compute(value, msg, has_msg, active, agg, superstep,
                    _s=steps):
            import jax.numpy as jnp
            return value + jnp.where(has_msg, msg, 0.0), superstep < _s

        def send(v, e, deg):
            return v * 0.25 + e

        gids, gvals, _ = run_pregel(tctx, ids, v0, (src, dst), compute,
                                    send, combine=combine,
                                    edge_values=w)
        assert tctx.scheduler._pregel_device_used, (seed, combine)
        hids, hvals, _ = _pregel_host(ids, v0, (src, dst), compute,
                                      send, combine, w, None, None,
                                      None, 80)
        assert np.array_equal(gids, hids)
        assert np.allclose(gvals, hvals), (seed, combine)


def _object_pagerank(ctx, n=48, steps=8):
    import operator
    from dpark_tpu.bagel import Bagel, BasicCombiner, Edge, Message, Vertex

    class PR:
        def __init__(self, n, steps):
            self.n, self.steps = n, steps

        def __call__(self, vert, msg, agg, s):
            if s == 0:
                value = vert.value
            else:
                value = (0.15 / self.n
                         + 0.85 * (msg if msg is not None else 0.0))
            active = s < self.steps
            v = Vertex(vert.id, value, vert.outEdges, active)
            if active and vert.outEdges:
                share = value / len(vert.outEdges)
                return (v, [Message(e.target_id, share)
                            for e in vert.outEdges])
            return (v, [])

    links = {i: [(i + 1) % n, (i * 5 + 2) % n] for i in range(n)}
    verts = ctx.parallelize(
        [(i, Vertex(i, 1.0 / n, [Edge(t) for t in ts]))
         for i, ts in links.items()], 4)
    msgs = ctx.parallelize([], 4)
    final = Bagel.run(ctx, verts, msgs, PR(n, steps),
                      combiner=BasicCombiner(operator.add))
    return {vid: v.value for vid, v in final.collect()}


def test_object_bagel_auto_columnarizes(tctx):
    """VERDICT r3 #7: a numeric object-Bagel program rides the device
    Pregel (_pregel_device_used) with parity vs the local master."""
    from dpark_tpu import DparkContext
    got = _object_pagerank(tctx)
    assert getattr(tctx.scheduler, "_pregel_device_used", False), \
        "object program did not ride the device"
    lctx = DparkContext("local")
    exp = _object_pagerank(lctx)
    lctx.stop()
    assert set(got) == set(exp)
    for k in exp:
        assert abs(got[k] - exp[k]) < 1e-9, (k, got[k], exp[k])
    assert abs(sum(got.values()) - 1.0) < 1e-6


def test_object_bagel_fallback_for_list_combiner(tctx):
    """Default (list) Combiner is not a monoid: warn-and-fallback to
    the host object path, results still correct."""
    from dpark_tpu.bagel import Bagel, Edge, Message, Vertex

    def compute(vert, msgs, agg, s):
        total = sum(msgs) if msgs else 0
        v = Vertex(vert.id, vert.value + total, vert.outEdges, s < 2)
        if s < 2 and vert.outEdges:
            return (v, [Message(e.target_id, 1) for e in vert.outEdges])
        return (v, [])

    verts = tctx.parallelize(
        [(i, Vertex(i, 0, [Edge((i + 1) % 4)])) for i in range(4)], 2)
    msgs = tctx.parallelize([], 2)
    final = Bagel.run(tctx, verts, msgs, compute)
    out = dict(final.collect())
    assert not getattr(tctx.scheduler, "_pregel_device_used", False)
    # each vertex receives one message of value 1 at supersteps 1 and 2
    assert all(out[i].value == 2 for i in range(4)), \
        {i: out[i].value for i in range(4)}


def _run_both(program_fn, build_fn):
    """Run an object-Bagel program on the tpu and local masters,
    returning ({id: value} tpu, {id: value} local, device_used)."""
    from dpark_tpu import DparkContext
    from dpark_tpu.bagel import Bagel
    outs = []
    used = False
    for master in ("tpu", "local"):
        c = DparkContext(master)
        c.start()
        try:
            verts, msgs, combiner = build_fn(c)
            final = Bagel.run(c, verts, msgs, program_fn,
                              combiner=combiner)
            outs.append({vid: v.value for vid, v in final.collect()})
            if master == "tpu":
                used = getattr(c.scheduler, "_pregel_device_used",
                               False)
        finally:
            c.stop()
    return outs[0], outs[1], used


def test_object_bagel_no_mail_sees_none():
    """A vertex with NO in-edges gets the literal msg=None on the
    object contract; the columnarized device path must take the same
    branch, not deliver the combine identity (r4 review finding)."""
    import operator
    from dpark_tpu.bagel import BasicCombiner, Edge, Message, Vertex

    def compute(vert, msg, agg, s):
        # no-mail branch doubles; mail branch is msg+1 — identity(0)
        # delivered as "mail" would silently diverge
        newv = (msg + 1.0) if msg is not None else (vert.value * 2.0)
        active = s < 3
        v = Vertex(vert.id, newv, vert.outEdges, active)
        if active and vert.outEdges:
            return (v, [Message(e.target_id, newv)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        edges = {0: 1, 1: 2, 2: 3, 3: 1}     # vertex 0 has no in-edges
        verts = c.parallelize(
            [(i, Vertex(i, 1.0, [Edge(t)])) for i, t in edges.items()],
            2)
        return verts, c.parallelize([], 2), BasicCombiner(operator.add)

    tpu, local, used = _run_both(compute, build)
    assert used, "program did not ride the device"
    assert tpu == local, (tpu, local)
    assert local[0] == 16.0                  # doubled every superstep


def test_object_bagel_empty_emission_sends_nothing():
    """An ACTIVE vertex whose compute returns (v, []) must not send —
    phantom identity messages would rewake halted neighbors (r4 review
    finding)."""
    import operator
    from dpark_tpu.bagel import BasicCombiner, Edge, Vertex

    def compute(vert, msg, agg, s):
        newv = vert.value + 1.0              # counts its invocations
        active = bool(vert.outEdges) and s < 3
        return (Vertex(vert.id, newv, vert.outEdges, active), [])

    def build(c):
        verts = c.parallelize(
            [(0, Vertex(0, 0.0, [Edge(1)])), (1, Vertex(1, 0.0, []))],
            2)
        return verts, c.parallelize([], 2), BasicCombiner(operator.add)

    tpu, local, used = _run_both(compute, build)
    assert used, "program did not ride the device"
    assert tpu == local, (tpu, local)
    assert local[1] == 1.0                   # invoked once, then halted


def test_object_bagel_halt_and_send_delivers():
    """Messages from a vertex that emits and HALTS in the same
    superstep are still delivered (the object contract's semantics;
    an active-gated device send would drop them — r4 review finding)."""
    import operator
    from dpark_tpu.bagel import BasicCombiner, Edge, Message, Vertex

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0.0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, False)
        if s == 0 and vert.outEdges:
            return (v, [Message(e.target_id, 10.0)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        verts = c.parallelize(
            [(0, Vertex(0, 0.0, [Edge(1)])), (1, Vertex(1, 0.0, []))],
            2)
        return verts, c.parallelize([], 2), BasicCombiner(operator.add)

    tpu, local, used = _run_both(compute, build)
    assert used, "program did not ride the device"
    assert tpu == local, (tpu, local)
    assert local[1] == 10.0                  # woken by the halter's msg


def test_object_bagel_widening_dtype_falls_back():
    """A later superstep emitting a WIDER message dtype than discovery
    saw at s=0 must fall back to the host object path (parity), never
    silently truncate on device (r4 review finding)."""
    import operator
    from dpark_tpu.bagel import BasicCombiner, Edge, Message, Vertex

    def compute(vert, msg, agg, s):
        v = Vertex(vert.id, vert.value + 1, vert.outEdges, s < 2)
        if s < 2 and vert.outEdges:
            val = 1 if s == 0 else 0.5       # int at s=0, float later
            return (v, [Message(e.target_id, val)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        verts = c.parallelize(
            [(i, Vertex(i, 0, [Edge((i + 1) % 4)])) for i in range(4)],
            2)
        return verts, c.parallelize([], 2), BasicCombiner(operator.add)

    tpu, local, used = _run_both(compute, build)
    assert not used, "widening program must not stay on device"
    assert tpu == local, (tpu, local)
