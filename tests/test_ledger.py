"""Resource attribution plane (ISSUE 15): per-tenant mesh ledger,
program cost profiles, utilization/conservation.

The contracts under test:

* PARITY — the ledger sink OBSERVES, it never perturbs: on/off runs
  are bit-identical across the chaos matrix (the health-plane bar),
  and off mode is one `is None` check per trace record.
* ACCOUNTS — merges are associative/commutative, memory stays bounded
  past the key cap (overflow folds into coarse accounts so totals
  stay honest), and device/compile/lock/HBM activity lands on the
  right (tenant, job, stage, signature) key.
* MESH LOCK — acquisition wait is measured (the new mesh.lock span),
  hold time meters mesh-busy, and the conservation check reconciles
  attributed occupancy with the meter under two concurrent tenants.
* COST PROFILES — compile-time jax cost analysis persists to the
  adapt store keyed by the plan signature and reads back in a FRESH
  process (the items-2/3 pricing prior).
* PROGRAM CACHE — per-job hit/miss counts are EXACT under concurrency
  (the PR 9 caveat, closed).
* CROSS-PROCESS — multiproc workers' fetch activity surfaces in the
  driver's merged accounts via the O(1) ledger-<host>-<pid>.jsonl
  sidecar (the health-file idiom).
* CONSUMERS — /api/ledger, per-tenant /metrics counters, the web UI
  table, dtrace --ledger (offline twin == live), flight dumps, and
  /api/health's top-k + attribution evidence.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from dpark_tpu import conf, faults, health, ledger, trace


@pytest.fixture(autouse=True)
def _clean_planes():
    """Every test starts and ends with fresh sinks and no trace/chaos
    planes; the cost-capture seen-set resets so per-test stores see
    their own captures."""
    from dpark_tpu import service
    trace.configure("off")
    faults.configure(None)
    health.configure("on")
    ledger.configure("on")
    ledger.reset_cost_capture()
    yield
    service.shutdown()
    trace.configure("off")
    faults.configure(None)
    health.configure("on")
    ledger.configure("on")
    ledger.reset_cost_capture()


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


def _reduce_job(c, n=500, parts=4, reduce_parts=3):
    return dict(c.parallelize([(i % 5, 1) for i in range(n)], parts)
                .reduceByKey(lambda a, b: a + b,
                             reduce_parts).collect())


def _device_data(n=20000, keys=37):
    import numpy as np
    from dpark_tpu import Columns
    i = np.arange(n, dtype=np.int64)
    return Columns(i % keys, i & 0xFF)


# ---------------------------------------------------------------------------
# accounts
# ---------------------------------------------------------------------------

def test_account_merge_associative_and_roundtrip():
    import random
    rng = random.Random(11)
    parts = []
    for _ in range(4):
        a = ledger.Account()
        a.device_ms = rng.random() * 100
        a.fetches = rng.randrange(50)
        a.hbm_byte_s = rng.random() * 1e6
        a.compiles = rng.randrange(3)
        parts.append(a)

    def fold(order):
        acc = ledger.Account()
        for i in order:
            acc.merge(ledger.Account.from_dict(parts[i].to_dict()))
        return acc.to_dict()

    a = fold([0, 1, 2, 3])
    b = fold([3, 1, 0, 2])
    left = ledger.merge_account_digests(
        ledger.merge_account_digests(parts[0].to_dict(),
                                     parts[1].to_dict()),
        ledger.merge_account_digests(parts[2].to_dict(),
                                     parts[3].to_dict()))
    assert a == b == left
    assert ledger.Account.from_dict(a).fetches == \
        sum(p.fetches for p in parts)
    # garbage digests fold to empty, never raise
    assert ledger.Account.from_dict(
        {"fetches": "x", "bogus": 1}).to_dict() == {}


def test_key_string_roundtrip():
    for key in ((3, 5, "abc"), (None, None, None), (7, None, "~")):
        assert ledger.parse_key(ledger._key_str(key)) == key


def test_sink_bounded_past_key_cap(monkeypatch):
    monkeypatch.setattr(conf, "LEDGER_MAX_KEYS", 8)
    s = ledger.LedgerSink()
    for i in range(1000):
        s.fold({"name": "stage.exec", "dur": 0.001, "job": 1,
                "stage": i, "args": {"sig": "s%d" % i}})
    assert len(s.accounts) <= 8 + 16
    assert s.dropped_keys > 0
    # totals stay honest: every observation landed somewhere
    total = sum(a.stages for a in s.accounts.values())
    assert total == 1000


def test_resident_server_attribution_survives_job_churn(monkeypatch):
    """Regression (review finding): a long-lived server's finished
    jobs RETIRE into the bounded per-(tenant, sig) archive, so live
    keys never exhaust the cap into the unattributed overflow —
    tenant attribution and conservation stay exact forever."""
    monkeypatch.setattr(conf, "LEDGER_MAX_KEYS", 8)
    s = ledger.LedgerSink()
    for job in range(1, 501):
        tenant = "tenant-%d" % (job % 2)
        s.note_job(job, tenant)
        s.fold({"name": "stage.exec", "dur": 0.01, "job": job,
                "stage": 1, "ts": float(job), "args": {"sig": "P"}})
        s.fold({"name": "mesh.lock", "dur": 0.0, "job": job,
                "stage": 1, "ts": float(job),
                "args": {"hold_s": 0.01}})
        s.fold({"name": "job", "ts": float(job), "dur": 0.01,
                "job": job, "args": {"client": tenant,
                                     "state": "done"}})
    assert not s.accounts                # everything retired
    assert s.dropped_keys == 0           # the cap was never pressed
    snap = s.snapshot(now=1000.0)
    # every one of the 500 jobs' time still attributes to its tenant
    for t in ("tenant-0", "tenant-1"):
        assert snap["tenants"][t]["device_ms"] == \
            pytest.approx(2500.0), snap["tenants"]
    cons = ledger.conservation(
        meter={"busy_s": 5.0, "wall_s": 500.0}, snap=snap)
    assert cons["ok"] is True and cons["ratio"] == 1.0, cons
    top = ledger.top_programs(snap=snap)
    assert top[0]["sig"] == "P" and top[0]["device_s"] == 5.0


def test_off_mode_is_one_predicate():
    ledger.configure("off")
    assert ledger._SINK is None
    assert ledger.mode() == "off"
    assert ledger.summary() == {"mode": "off", "tenants": {},
                                "accounts": 0}
    assert ledger.tenant_totals() == {}
    with pytest.raises(ValueError):
        ledger.configure("loud")


# ---------------------------------------------------------------------------
# parity: the sink observes, never perturbs (chaos matrix)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [
    None,
    "shuffle.fetch:p=0.3,seed=11,times=3",
    "shuffle.spill_write:nth=1,kind=corrupt",
])
def test_ledger_on_off_parity_chaos_matrix(ctx, tmp_path, spec):
    pairs = [(i % 11, i) for i in range(500)]

    def run():
        faults.configure(spec)
        try:
            return dict(ctx.parallelize(pairs, 4)
                        .groupByKey(3)
                        .mapValues(sorted).collect())
        finally:
            faults.configure(None)

    ledger.configure("off")
    expected = run()                     # ledger off, trace off
    for mode in ("ring", "spool"):
        trace.configure(mode, str(tmp_path / mode))
        ledger.configure("on")
        try:
            assert run() == expected, (mode, spec)
            snap = ledger.snapshot()
            assert snap["folded"] > 0
            # finished jobs' accounts compact into the archive
            assert snap["accounts"] or snap["archive"], snap
        finally:
            trace.configure("off")
        trace.configure(mode, str(tmp_path / (mode + "-off")))
        ledger.configure("off")
        try:
            assert run() == expected, (mode, spec)
        finally:
            trace.configure("off")
        ledger.configure("on")


@pytest.mark.parametrize("spec", [
    None,
    "shuffle.fetch:p=0.3,seed=11,times=3",
])
def test_ledger_parity_device(tctx2, tmp_path, spec):
    data = _device_data(4000)

    def run():
        faults.configure(spec)
        try:
            return dict(tctx2.parallelize(data, 2)
                        .reduceByKey(lambda a, b: a + b, 2).collect())
        finally:
            faults.configure(None)

    ledger.configure("off")
    expected = run()
    trace.configure("spool", str(tmp_path / "dev"))
    ledger.configure("on")
    try:
        assert run() == expected
        snap = ledger.snapshot()
        # device execution landed in an account keyed by the adapt
        # program signature (retired to the per-tenant archive once
        # the job span folded)
        sigs = [k.split("|", 1)[1]
                for k, d in snap["archive"].items()
                if d.get("device_ms")]
        assert any(s and s != ledger.OVERFLOW for s in sigs), snap
        # mesh occupancy folded from the mesh.lock spans
        assert snap["mesh"]["acquisitions"] > 0, snap["mesh"]
        assert snap["mesh"]["busy_s"] > 0
    finally:
        trace.configure("off")


# ---------------------------------------------------------------------------
# mesh lock: measured wait + busy meter
# ---------------------------------------------------------------------------

def test_mesh_lock_wait_measured_and_span_emitted(tmp_path):
    from dpark_tpu.backend.tpu.executor import _MeshLock
    trace.configure("ring")
    lock = _MeshLock()
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            entered.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    assert entered.wait(5)
    time.sleep(0.05)

    def waiter():
        with lock:
            pass

    w = threading.Thread(target=waiter)
    w.start()
    time.sleep(0.08)             # the waiter queues behind the holder
    release.set()
    w.join(5)
    t.join(5)
    assert lock.acquisitions == 2
    assert lock.contended == 1
    assert lock.wait_s >= 0.05, lock.wait_s
    assert lock.busy_s >= lock.wait_s
    spans = [r for r in trace.snapshot() if r["name"] == "mesh.lock"]
    assert len(spans) == 2
    waited = [r for r in spans if r["dur"] > 0.04]
    assert len(waited) == 1, spans
    assert waited[0]["args"]["hold_s"] >= 0
    # reentrant re-acquire counts one acquisition, one hold
    with lock:
        with lock:
            pass
    assert lock.acquisitions == 3
    trace.configure("off")


def test_lock_wait_attributed_to_waiting_job():
    s = ledger.LedgerSink()
    s.note_job(7, "tenant-x")
    s.fold({"name": "mesh.lock", "dur": 0.25, "job": 7, "stage": 2,
            "ts": 100.0, "args": {"hold_s": 0.5}})
    s.fold({"name": "mesh.lock", "dur": 0.0, "job": 8, "stage": 3,
            "ts": 101.0, "args": {"hold_s": 0.25}})
    snap = s.snapshot(now=102.0)
    t = snap["tenants"]["tenant-x"]
    assert t["lock_wait_ms"] == 250.0
    assert t["lock_hold_ms"] == 500.0
    assert snap["mesh"]["busy_s"] == 0.75
    assert snap["mesh"]["contended"] == 1


# ---------------------------------------------------------------------------
# HBM byte-seconds
# ---------------------------------------------------------------------------

def test_hbm_byte_seconds_accrue_on_release_and_spill():
    s = ledger.LedgerSink()
    s.fold({"name": "hbm.store", "job": 1, "stage": 2, "ts": 10.0,
            "args": {"sid": 5, "bytes": 1000}})
    s.fold({"name": "hbm.store", "job": 1, "stage": 2, "ts": 10.0,
            "args": {"sid": 6, "bytes": 500}})
    # live gauge before any release
    snap = s.snapshot(now=12.0)
    assert snap["hbm_live_bytes"] == 1500
    assert snap["hbm_live_byte_s"] == pytest.approx(3000.0)
    s.fold({"name": "hbm.release", "ts": 13.0,
            "args": {"sid": 5, "bytes": 1000, "reason": "drop"}})
    s.fold({"name": "hbm.release", "ts": 14.0,
            "args": {"sid": 6, "bytes": 500, "reason": "spill"}})
    snap = s.snapshot(now=20.0)
    acct = snap["accounts"]["1|2|-"]
    # 1000 B x 3 s + 500 B x 4 s, attributed to the STORING account
    assert acct["hbm_byte_s"] == pytest.approx(5000.0)
    assert acct["hbm_spills"] == 1
    assert snap["hbm_live_bytes"] == 0
    # double release is a no-op, not a crash
    s.fold({"name": "hbm.release", "ts": 15.0,
            "args": {"sid": 6, "bytes": 500}})


def test_hbm_release_settles_after_tracing_turned_off(tctx2):
    """Regression (review finding): a store registered while traced
    but released after trace.configure("off") must still settle the
    sink's residency entry — else the live gauge reports freed memory
    forever and the byte-seconds never accrue."""
    trace.configure("ring")
    dict(tctx2.parallelize(_device_data(6000), 2)
         .reduceByKey(lambda a, b: a + b, 2).collect())
    assert ledger.snapshot()["hbm_live_bytes"] > 0
    trace.configure("off")
    ex = tctx2.scheduler.executor
    for sid in list(ex.shuffle_store):
        ex.drop_shuffle(sid)
    snap = ledger.snapshot()
    assert snap["hbm_live_bytes"] == 0, snap
    accrued = sum(d.get("hbm_byte_s", 0.0)
                  for d in list(snap["accounts"].values())
                  + list(snap["archive"].values()))
    assert accrued > 0, snap


def test_hbm_byte_seconds_on_device_store_drop(tctx2):
    trace.configure("ring")
    try:
        got = dict(tctx2.parallelize(_device_data(8000), 2)
                   .reduceByKey(lambda a, b: a + b, 2).collect())
        assert len(got) == 37
        ex = tctx2.scheduler.executor
        assert ledger.snapshot()["hbm_live_bytes"] > 0
        for sid in list(ex.shuffle_store):
            ex.drop_shuffle(sid)
        snap = ledger.snapshot()
        assert snap["hbm_live_bytes"] == 0
        # the job retired before the drop: accrual lands in the
        # tenant's archive, never a resurrected live account
        accrued = sum(d.get("hbm_byte_s", 0.0)
                      for d in snap["archive"].values())
        assert accrued > 0, snap
        assert not snap["accounts"], snap["accounts"]
    finally:
        trace.configure("off")


# ---------------------------------------------------------------------------
# conservation: two concurrent tenants on one mesh
# ---------------------------------------------------------------------------

def test_conservation_two_concurrent_tenants(tmp_path):
    from dpark_tpu import DparkContext, service
    from dpark_tpu.service import ClientScheduler
    trace.configure("ring")
    ctx = DparkContext("service:tpu:2")
    ctx.start()
    try:
        srv = ctx.scheduler.server
        ta = ClientScheduler(srv, client="tenant-a")
        tb = ClientScheduler(srv, client="tenant-b")
        data = _device_data(30000, 97)

        def run(tenant, out, key):
            # each tenant builds its OWN graph so both genuinely
            # compute on the mesh (a shared RDD would let the second
            # job reuse the first's shuffle outputs)
            rdd = ctx.parallelize(data, 2) \
                .reduceByKey(lambda a, b: a + b, 2)
            got = dict(x for part in tenant.run_job(
                rdd, lambda it: list(it)) for x in part)
            out[key] = got

        got = {}
        th = threading.Thread(target=run, args=(ta, got, "a"))
        th.start()
        run(tb, got, "b")
        th.join(60)
        assert len(got["a"]) == 97 and got["a"] == got["b"]
        totals = ledger.tenant_totals()
        assert totals["tenant-a"]["device_seconds"] > 0, totals
        assert totals["tenant-b"]["device_seconds"] > 0, totals
        cons = ledger.conservation(ctx.scheduler)
        # every mesh-busy second names a tenant: attributed occupancy
        # reconciles with the lock meter (the ISSUE 15 acceptance has
        # a 10% bar; job-ctx attribution makes this ~exact)
        assert cons["ok"] is True, cons
        assert cons["ratio"] >= 0.9, cons
        util = ledger.utilization(ctx.scheduler)
        assert util["meter"]["acquisitions"] > 0
        assert 0.0 <= util["busy_frac"] <= 1.0
    finally:
        trace.configure("off")
        ctx.stop()
        service.shutdown()


# ---------------------------------------------------------------------------
# conservation: cache-served jobs (ISSUE 18 satellite)
# ---------------------------------------------------------------------------

def test_resultcache_events_attribute_by_tenant():
    """The shared result cache runs NO job for a served query, so its
    events carry the tenant explicitly: residency byte-seconds bill
    the STORING tenant at release, hits/served-bytes the SERVED
    tenant — and a cache-served query conserves trivially (zero scan
    device-seconds, nothing on the mesh to reconcile)."""
    s = ledger.LedgerSink()
    s.fold({"name": "resultcache.store", "ts": 10.0,
            "args": {"sid": "k1", "bytes": 1000,
                     "tenant": "tenant-a"}})
    snap = s.snapshot(now=12.0)
    assert snap["resultcache_live_bytes"] == 1000
    assert snap["resultcache_live_byte_s"] == pytest.approx(2000.0)
    s.fold({"name": "resultcache.serve", "ts": 11.0,
            "args": {"sid": "k1", "bytes": 1000, "tier": "full",
                     "tenant": "tenant-b"}})
    s.fold({"name": "resultcache.release", "ts": 15.0,
            "args": {"sid": "k1", "bytes": 1000, "reason": "evict",
                     "tenant": "tenant-a"}})
    snap = s.snapshot(now=15.0)
    assert snap["resultcache_live_bytes"] == 0
    totals = ledger.tenant_totals_from_snapshot(snap)
    a, b = totals["tenant-a"], totals["tenant-b"]
    # 1000 bytes held 10.0..15.0 bills the storing tenant
    assert a["resultcache_byte_seconds"] == pytest.approx(5000.0)
    assert a["resultcache_hits"] == 0
    # the hit bills the SERVED tenant — at ZERO device-seconds
    assert b["resultcache_hits"] == 1
    assert b["resultcache_served_bytes"] == 1000
    assert b["device_seconds"] == 0.0
    # nothing ran on the mesh: the conservation check has nothing to
    # reconcile and must NOT flag the served query as unattributed
    cons = ledger.conservation(meter={"busy_s": 0.0, "wall_s": 5.0},
                               snap=snap)
    assert cons["ok"] is not False, cons
    assert cons["attributed_device_s"] == 0.0


def test_conservation_holds_with_cache_served_tenant():
    """One tenant pays the scan (mesh-busy, job-attributed), another
    is served from the cache (no job): attributed occupancy still
    reconciles exactly — the served tenant adds hits, not holds."""
    s = ledger.LedgerSink()
    s.note_job(1, "tenant-a")
    s.fold({"name": "stage.exec", "dur": 0.4, "job": 1, "stage": 1,
            "ts": 10.0, "args": {"sig": "Q"}})
    s.fold({"name": "mesh.lock", "dur": 0.0, "job": 1, "stage": 1,
            "ts": 10.0, "args": {"hold_s": 0.4}})
    s.fold({"name": "resultcache.store", "ts": 10.5,
            "args": {"sid": "kq", "bytes": 512,
                     "tenant": "tenant-a"}})
    s.fold({"name": "job", "ts": 10.6, "dur": 0.5, "job": 1,
            "args": {"client": "tenant-a", "state": "done"}})
    s.fold({"name": "resultcache.serve", "ts": 11.0,
            "args": {"sid": "kq", "bytes": 512, "tier": "full",
                     "tenant": "tenant-b"}})
    snap = s.snapshot(now=12.0)
    cons = ledger.conservation(meter={"busy_s": 0.4, "wall_s": 2.0},
                               snap=snap)
    # every mesh-busy second names tenant-a; the served tenant-b
    # consumed none and broke nothing
    assert cons["ok"] is True and cons["ratio"] == 1.0, cons
    totals = ledger.tenant_totals_from_snapshot(snap)
    assert totals["tenant-a"]["device_seconds"] == \
        pytest.approx(0.4)
    assert totals["tenant-b"]["device_seconds"] == 0.0
    assert totals["tenant-b"]["resultcache_hits"] == 1


def test_cache_served_query_end_to_end_ledger(tmp_path):
    """Live integration: a repeated tabular group-by under
    trace=ring + ledger=on + resultcache=mem.  The second tenant's
    query is served from the cache — the ledger shows the hit billed
    to it with zero scan device work."""
    from dpark_tpu import DparkContext, resultcache
    from dpark_tpu.tabular import write_tabular
    d = str(tmp_path / "tab")
    os.makedirs(d)
    write_tabular(os.path.join(d, "part-00000.tab"), ["t", "k", "a"],
                  [(i, i % 7, i % 50) for i in range(4000)],
                  chunk_rows=1000)
    trace.configure("ring")
    ledger.configure("on")
    resultcache.configure(mode="mem",
                          cache_dir=str(tmp_path / "rc"))
    ctx = DparkContext("local")
    try:
        def q():
            return ctx.tabular(d, ["t", "k", "a"]).asTable("e") \
                .where("t >= 1000").groupBy("k", "sum(a) as s")
        with resultcache.tenant("tenant-a"):
            cold = sorted(q().collect())
        with resultcache.tenant("tenant-b"):
            warm = sorted(q().collect())
        assert warm == cold
        totals = ledger.tenant_totals()
        b = totals["tenant-b"]
        assert b["resultcache_hits"] == 1, totals
        assert b["resultcache_served_bytes"] > 0
        assert b["device_seconds"] == 0.0
        assert totals["tenant-a"]["resultcache_hits"] == 0
        cons = ledger.conservation()
        assert cons["ok"] is not False, cons
    finally:
        resultcache.configure(mode="off")
        trace.configure("off")
        ledger.configure("off")
        ctx.stop()


# ---------------------------------------------------------------------------
# program cost profiles (the items-2/3 pricing prior)
# ---------------------------------------------------------------------------

def test_program_cost_profile_roundtrip_fresh_process(
        tctx2, tmp_path, monkeypatch):
    from dpark_tpu import adapt
    monkeypatch.setattr(conf, "LEDGER_COST", "compile")
    store = str(tmp_path / "adapt")
    adapt.configure(mode="observe", store_dir=store)
    trace.configure("ring")
    try:
        got = dict(tctx2.parallelize(_device_data(8000), 2)
                   .reduceByKey(lambda a, b: a + b, 2).collect())
        assert len(got) == 37
        profiles = adapt.program_costs()
        assert profiles, "no cost profile captured"
        key, prof = next(iter(profiles.items()))
        assert prof["flops"] > 0, prof
        assert prof["bytes_accessed"] > 0, prof
        # the compile path captured measured memory analysis
        assert prof.get("peak_hbm_bytes", 0) > 0, prof
        assert key in adapt.summary()["programs"]
        # a FRESH process reads the persisted profile back (the
        # acceptance criterion: pricing before the first observed run)
        out = subprocess.run(
            [sys.executable, "-c",
             "import json\n"
             "from dpark_tpu import adapt\n"
             "adapt.configure(mode='observe', store_dir=%r)\n"
             "print(json.dumps(adapt.program_costs()))" % store],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        fresh = json.loads(out.stdout.strip().splitlines()[-1])
        assert fresh.get(key, {}).get("flops") == prof["flops"], fresh
    finally:
        trace.configure("off")
        adapt.configure()


def test_cost_capture_once_per_signature(tctx2, tmp_path, monkeypatch):
    from dpark_tpu import adapt
    adapt.configure(mode="observe", store_dir=str(tmp_path / "a"))
    trace.configure("ring")
    try:
        data = _device_data(6000)
        for _ in range(3):
            dict(tctx2.parallelize(data, 2)
                 .reduceByKey(lambda a, b: a + b, 2).collect())
        events = [r for r in trace.snapshot()
                  if r["name"] == "ledger.cost"]
        sigs = [r["args"]["sig"] for r in events]
        assert len(sigs) == len(set(sigs)), sigs
    finally:
        trace.configure("off")
        adapt.configure()


def test_cost_capture_off_mode_records_nothing(
        tctx2, tmp_path, monkeypatch):
    from dpark_tpu import adapt
    monkeypatch.setattr(conf, "LEDGER_COST", "off")
    adapt.configure(mode="observe", store_dir=str(tmp_path / "a"))
    trace.configure("ring")
    try:
        dict(tctx2.parallelize(_device_data(6000), 2)
             .reduceByKey(lambda a, b: a + b, 2).collect())
        assert adapt.program_costs() == {}
    finally:
        trace.configure("off")
        adapt.configure()


# ---------------------------------------------------------------------------
# exact per-job program-cache counts (the PR 9 caveat, closed)
# ---------------------------------------------------------------------------

def test_program_cache_per_job_counts_exact_across_threads():
    from dpark_tpu.backend.tpu.executor import _ProgramCache
    pc = _ProgramCache(cap=0)
    tls = threading.local()
    pc._job_of = lambda: getattr(tls, "job", None)
    errs = []

    def worker(job, keys):
        tls.job = job
        try:
            for k in keys:
                if k not in pc:
                    pc[k] = k
                assert k in pc           # second probe: hit
        except Exception as e:           # pragma: no cover
            errs.append(e)

    t1 = threading.Thread(target=worker,
                          args=(1, ["a%d" % i for i in range(50)]))
    t2 = threading.Thread(target=worker,
                          args=(2, ["b%d" % i for i in range(80)]))
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert not errs
    assert pc.job_stats(1) == {"hits": 50, "misses": 50}
    assert pc.job_stats(2) == {"hits": 80, "misses": 80}
    assert pc.job_stats(99) == {"hits": 0, "misses": 0}


def test_program_cache_exact_under_overlapping_jobs():
    """Regression (ISSUE 15 satellite): a warm job's
    record["program_cache"] used to be a process-wide delta, so a
    CONCURRENT job's compiles leaked into it.  With per-job tagging
    the warm job reports misses == 0 even while another tenant
    compiles a different program mid-flight."""
    from dpark_tpu import DparkContext, service
    from dpark_tpu.service import ClientScheduler
    ctx = DparkContext("service:tpu:2")
    ctx.start()
    try:
        srv = ctx.scheduler.server
        ta = ClientScheduler(srv, client="tenant-warm")
        tb = ClientScheduler(srv, client="tenant-cold")
        warm_rdd = ctx.parallelize(_device_data(20000), 2) \
            .reduceByKey(lambda a, b: a + b, 2)

        def collect(tenant, rdd):
            return dict(x for part in tenant.run_job(
                rdd, lambda it: list(it)) for x in part)

        # pass 1: compile tenant-warm's program
        ref = collect(ta, warm_rdd)
        # a DIFFERENT program (different key space + min merge) the
        # cold tenant compiles while the warm job re-runs
        cold_rdd = ctx.parallelize(_device_data(60000, 251), 2) \
            .reduceByKey(min, 2)
        got = {}
        th = threading.Thread(
            target=lambda: got.update(cold=collect(tb, cold_rdd)))
        th.start()
        warm2 = collect(ta, warm_rdd)
        th.join(60)
        assert warm2 == ref
        assert len(got["cold"]) == 251
        sched = srv.scheduler
        warm_recs = [r for r in sched.history
                     if r.get("client") == "tenant-warm"]
        assert len(warm_recs) == 2
        pc = warm_recs[-1]["program_cache"]
        # EXACT: zero misses even though tenant-cold compiled during
        # the overlap (the old process-wide delta would count them)
        assert pc["misses"] == 0, pc
        assert pc["hits"] >= 1, pc
        cold_pc = [r for r in sched.history
                   if r.get("client") == "tenant-cold"][-1][
                       "program_cache"]
        assert cold_pc["misses"] >= 1, cold_pc
    finally:
        ctx.stop()
        service.shutdown()


# ---------------------------------------------------------------------------
# cross-process: multiproc worker attribution via the O(1) sidecar
# ---------------------------------------------------------------------------

@pytest.fixture()
def fresh_forkserver():
    from multiprocessing import forkserver

    def stop():
        try:
            forkserver._forkserver._stop()
        except Exception:
            pass

    stop()
    yield
    stop()


def test_worker_accounts_surface_on_driver(fresh_forkserver, pctx,
                                           tmp_path):
    d = str(tmp_path / "mp")
    trace.configure("spool", d)
    try:
        assert _reduce_job(pctx, n=400) == {k: 80 for k in range(5)}
        # the driver process itself fetched nothing...
        own = ledger.snapshot()["accounts"]
        assert not any(a.get("fetches") for a in own.values()), own
        # ...but the merged view carries the workers' accounts,
        # attributed to the job (task._trace_job ships the id)
        merged = ledger.merged_account_digests()
        fetched = {k: a for k, a in merged.items()
                   if a.get("fetches")}
        assert fetched, merged
        assert any(ledger.parse_key(k)[0] is not None
                   for k in fetched), fetched
        # the sidecar files exist and are O(1): ONE record each,
        # atomically rewritten (the health-<host>-<pid>.jsonl idiom)
        sidecars = [fn for fn in os.listdir(d)
                    if fn.startswith("ledger-")]
        assert sidecars, os.listdir(d)
        from dpark_tpu.utils import unframe_jsonl
        for fn in sidecars:
            with open(os.path.join(d, fn), "rb") as f:
                recs, skipped = unframe_jsonl(f.read())
            assert len(recs) == 1 and skipped == 0, fn
            assert recs[0]["name"] == "process.ledger"
    finally:
        trace.configure("off")


# ---------------------------------------------------------------------------
# offline twin: dtrace --ledger vs the live snapshot
# ---------------------------------------------------------------------------

def _load_dtrace():
    from tests.conftest import load_tool
    return load_tool("dtrace")


def test_dtrace_ledger_matches_live(tctx2, tmp_path, capsys):
    d = str(tmp_path / "spool")
    trace.configure("spool", d)
    ledger.configure("on")           # fresh sink scoped to this run
    got = dict(tctx2.parallelize(_device_data(8000), 2)
               .reduceByKey(lambda a, b: a + b, 2).collect())
    assert len(got) == 37
    live = ledger.snapshot()
    trace.configure("off")
    dtrace = _load_dtrace()
    assert dtrace.main(["--ledger", "--dir", d]) == 0
    offline = json.loads(capsys.readouterr().out)
    # the offline twin folded the SAME records the live sink saw:
    # accounts agree exactly (byte-second GAUGES depend on the wall
    # clock and are excluded by construction — accrual-at-release is
    # in the accounts)
    assert offline["accounts"] == live["accounts"]
    assert offline["archive"] == live["archive"]
    assert offline["mesh"] == live["mesh"]
    # the twin's tenants field ships the LIVE rollup shape
    assert offline["tenants"] == \
        ledger.tenant_totals_from_snapshot(live)
    assert "device_seconds" in offline["tenants"]["local"]
    assert offline["job_tenant"] == live["job_tenant"]
    assert offline["conservation"]["attributed_device_s"] == \
        ledger.conservation(snap=live)["attributed_device_s"]
    # empty spool fails (the CI gate contract)
    assert dtrace.main(["--ledger", "--dir",
                        str(tmp_path / "empty")]) == 1


# ---------------------------------------------------------------------------
# consumers: /api/ledger, /metrics, web page, flight, /api/health
# ---------------------------------------------------------------------------

def test_api_ledger_endpoint_and_tenant_metrics(tctx2):
    from dpark_tpu.web import render_metrics, start_ui
    trace.configure("ring")
    try:
        dict(tctx2.parallelize(_device_data(8000), 2)
             .reduceByKey(lambda a, b: a + b, 2).collect())
        server, url = start_ui(tctx2.scheduler)
        try:
            with urllib.request.urlopen(url + "api/ledger") as r:
                assert r.status == 200
                api = json.loads(r.read().decode())
        finally:
            server.shutdown()
        assert api["mode"] == "on"
        assert api["accounts"] or api["archive"], api
        assert api["tenants"]["local"]["device_seconds"] > 0, api
        assert api["conservation"]["ratio"] is not None
        u = api["utilization"]
        assert abs(u["busy_frac"] + u["contended_frac"]
                   + u["idle_frac"] - 1.0) < 1e-6
        assert api["top_programs"], api
        body = render_metrics(tctx2.scheduler)
        assert 'dpark_tenant_device_seconds_total{tenant="local"}' \
            in body
        assert "dpark_tenant_hbm_byte_seconds_total" in body
        assert "dpark_tenant_lock_wait_seconds_total" in body
        assert "dpark_tenant_bulk_bytes_total" in body
    finally:
        trace.configure("off")


def test_page_has_ledger_table():
    from dpark_tpu import web
    assert "resource ledger" in web._PAGE
    assert "/api/ledger" in web._PAGE
    assert "conservation" in web._PAGE


def test_api_ledger_never_throws_when_off(ctx):
    ledger.configure("off")
    api = ledger.api_ledger(ctx.scheduler)
    assert api["mode"] == "off"
    assert json.dumps(api)


def test_flight_dump_carries_ledger(ctx, tmp_path):
    trace.configure("ring")
    _reduce_job(ctx)
    conf.DPARK_FLIGHT_DIR = str(tmp_path / "flight")
    try:
        health._flight_dumps = 0
        p = health.flight_dump("test", scheduler=ctx.scheduler)
        assert p
        recs = health.load_flight(p)
        led = [r for r in recs if r.get("kind") == "flight.ledger"]
        assert led, [r.get("kind") for r in recs]
        lsnap = led[0]["snapshot"]
        assert lsnap["accounts"] or lsnap["archive"], led[0]
    finally:
        conf.DPARK_FLIGHT_DIR = ""
        trace.configure("off")


def test_health_evidence_gains_ledger_topk(tctx2):
    trace.configure("ring")
    try:
        dict(tctx2.parallelize(_device_data(8000), 2)
             .reduceByKey(lambda a, b: a + b, 2).collect())
        api = health.api_health(tctx2.scheduler)
        ev = api["subsystems"]["executor"]["evidence"]
        assert ev.get("top_programs"), ev
        top = ev["top_programs"][0]
        assert top["device_s"] > 0 and top["sig"]
        att = api["subsystems"]["attribution"]
        assert att["grade"] in ("green", "yellow")
        assert "ratio" in att["evidence"]
        assert "mesh_busy_s" in att["evidence"]
    finally:
        trace.configure("off")


def test_untraced_master_never_grades_attribution_yellow(tctx2):
    """Regression (review finding): DPARK_TRACE=off with the ledger
    on (the DEFAULT config) — the always-on lock meter accrues busy
    time the sink never sees, which must read as 'nothing to
    conserve', not as unattributed consumption."""
    assert trace.mode() == "off"
    dict(tctx2.parallelize(_device_data(6000), 2)
         .reduceByKey(lambda a, b: a + b, 2).collect())
    cons = ledger.conservation(tctx2.scheduler)
    assert cons["mesh_busy_s"] > 0           # the meter did run
    assert cons["ratio"] is None and cons["ok"] is None, cons
    api = health.api_health(tctx2.scheduler)
    att = api["subsystems"].get("attribution")
    assert att is not None and att["grade"] == "green", att


def test_note_job_backstop_never_clobbers_new_tenant():
    """Regression (review finding): once the 4096-job backstop fires
    on every note_job, the evicted job's tenant must not leak into
    the NEW job's mapping."""
    s = ledger.LedgerSink()
    for job in range(4097):
        s.note_job(job, "tenant-old")
    s.note_job(5000, "tenant-new")       # backstop fires here too
    assert s.job_tenant[5000] == "tenant-new"


def test_conservation_graded_over_observed_window_only():
    """Regression (review finding): tracing enabled mid-life — busy
    time the meter accrued while untraced must not count against the
    attribution (the live path grades vs the sink's folded view)."""
    s = ledger.LedgerSink()
    s.note_job(1, "t")
    s.fold({"name": "mesh.lock", "dur": 0.0, "job": 1, "stage": 1,
            "ts": 10.0, "args": {"hold_s": 1.0}})
    s.fold({"name": "stage.exec", "dur": 1.0, "job": 1, "stage": 1,
            "ts": 10.0, "args": {"sig": "P"}})
    # lifetime meter saw 100 s of pre-tracing busy; the sink's folded
    # window saw 1 s, all attributed — conservation must hold
    cons = ledger.conservation(snap=s.snapshot(now=12.0))
    assert cons["ok"] is True and cons["ratio"] == 1.0, cons


def test_archive_key_with_pipe_in_tenant_name():
    s = ledger.LedgerSink()
    s.note_job(1, "team|alpha")
    s.fold({"name": "stage.exec", "dur": 0.5, "job": 1, "stage": 1,
            "ts": 1.0, "args": {"sig": "P"}})
    s.fold({"name": "job", "ts": 0.5, "dur": 1.0, "job": 1,
            "args": {"client": "team|alpha", "state": "done"}})
    top = ledger.top_programs(snap=s.snapshot(now=2.0))
    assert top == [{"sig": "P", "device_s": 0.5,
                    "tenant": "team|alpha"}], top


def test_ledger_summary_schema(ctx):
    trace.configure("ring")
    try:
        _reduce_job(ctx)
        s = ledger.summary()
        assert s["mode"] == "on"
        assert isinstance(s["tenants"], dict)
        assert s["accounts"] >= 1
        assert "conservation" in s and "mesh" in s
        assert json.dumps(s)
    finally:
        trace.configure("off")


def test_tenant_rollup_uses_note_job():
    s = ledger.LedgerSink()
    s.note_job(1, "alice")
    s.note_job(2, None)              # defaults to "local"
    s.fold({"name": "stage.exec", "dur": 0.5, "job": 1, "stage": 1,
            "ts": 1.0, "args": {"sig": "x"}})
    s.fold({"name": "stage.exec", "dur": 0.25, "job": 2, "stage": 1,
            "ts": 1.0, "args": {"sig": "x"}})
    snap = s.snapshot(now=2.0)
    assert snap["tenants"]["alice"]["device_ms"] == 500.0
    assert snap["tenants"]["local"]["device_ms"] == 250.0


def test_top_programs_name_the_dominant_tenant():
    """The evidence a yellow grade attaches must name the tenant that
    actually burned the device-seconds, regardless of account
    iteration order."""
    s = ledger.LedgerSink()
    s.note_job(1, "heavy")
    s.note_job(2, "light")
    s.fold({"name": "stage.exec", "dur": 10.0, "job": 1, "stage": 1,
            "ts": 1.0, "args": {"sig": "P"}})
    s.fold({"name": "stage.exec", "dur": 0.1, "job": 2, "stage": 1,
            "ts": 2.0, "args": {"sig": "P"}})
    s.fold({"name": "stage.exec", "dur": 0.5, "job": 2, "stage": 2,
            "ts": 3.0, "args": {"sig": "Q"}})
    top = ledger.top_programs(snap=s.snapshot(now=4.0))
    assert top[0] == {"sig": "P", "device_s": 10.1,
                      "tenant": "heavy"}
    assert top[1]["sig"] == "Q" and top[1]["tenant"] == "light"


def test_program_cache_job_bucket_survives_churn():
    """A long-running job that keeps probing must not lose its exact
    counts to newer short jobs (recency-refresh, not insertion-order
    eviction)."""
    from dpark_tpu.backend.tpu.executor import _ProgramCache
    pc = _ProgramCache(cap=0)
    tls = threading.local()
    pc._job_of = lambda: getattr(tls, "job", None)
    tls.job = 1
    pc["warm"] = 1
    assert "warm" in pc                 # job 1's bucket born
    for j in range(2, 200):             # 198 newer jobs churn through
        tls.job = j
        assert "warm" in pc
        tls.job = 1
        assert "warm" in pc             # job 1 keeps probing: refreshed
    assert pc.job_stats(1)["hits"] >= 198


def test_offline_fold_never_double_counts_retired_sidecars():
    """Regression (review finding): a worker's spans fold into
    accounts, the driver's job span retires them to the archive — the
    worker's cumulative sidecar digest for the same key must then be
    SKIPPED, not re-added as a fresh account."""
    recs = [
        {"name": "fetch.bucket", "cat": "shuffle", "ts": 1.0,
         "dur": 0.01, "job": 1, "stage": 2, "pid": 9,
         "args": {"peer": "local"}},
        {"name": "job", "cat": "sched", "ts": 0.5, "dur": 1.0,
         "job": 1, "pid": 1,
         "args": {"client": "tenant-w", "state": "done"}},
        {"name": "process.ledger", "cat": "counters", "ts": 2.0,
         "dur": 0.0, "pid": 9,
         "args": {"ledger": {"1|2|-": {"fetches": 1,
                                       "fetch_ms": 10.0}}}},
    ]
    s = ledger.fold_records(recs)
    snap = s.snapshot(now=3.0)
    total = sum(d.get("fetches", 0)
                for d in list(snap["accounts"].values())
                + list(snap["archive"].values()))
    assert total == 1, snap
    assert snap["tenants"]["tenant-w"]["fetches"] == 1


def test_offline_tenant_resolution_from_job_span():
    """The job span (emitted at job END) carries the client, so a
    spool alone resolves tenants — even though every stage span folds
    BEFORE the job span arrives."""
    recs = [
        {"name": "stage.exec", "cat": "exec", "ts": 1.0, "dur": 0.5,
         "job": 3, "stage": 1, "args": {"sig": "p"}},
        {"name": "job", "cat": "sched", "ts": 0.5, "dur": 1.2,
         "job": 3, "args": {"client": "tenant-z", "state": "done"}},
    ]
    s = ledger.fold_records(recs)
    snap = s.snapshot(now=2.0)
    assert snap["tenants"] == {"tenant-z": {"device_ms": 500.0,
                                            "stages": 1}}
