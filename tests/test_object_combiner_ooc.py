"""Out-of-core streaming for UNTRACEABLE combiners (VERDICT r2 ask #7).

The reference's disk-spilling external merger handles any combiner at
any size.  Here, a big source whose merge_combiners cannot trace (no
jnp semantics — e.g. math.gcd needs concrete ints) rides the spilled-
run stream: created combiners exchange on device, key-sorted runs land
on host disk per logical partition, and the user's merge folds each
sorted key group at export — O(1) combine state per key, input never
materialized whole.
"""

import math

import numpy as np
import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def small_chunks():
    """Shrink the wave size so modest test data exercises the stream."""
    import dpark_tpu.conf as conf
    was = conf.STREAM_CHUNK_ROWS, conf.STREAM_TEXT_BYTES
    conf.STREAM_CHUNK_ROWS = 512
    conf.STREAM_TEXT_BYTES = 20000
    yield
    conf.STREAM_CHUNK_ROWS, conf.STREAM_TEXT_BYTES = was


def _expect_gcd(keys, vals):
    out = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        out[k] = math.gcd(out[k], v) if k in out else v
    return out


def test_untraceable_merge_streams_columnar(tctx, small_chunks):
    """math.gcd: associative+commutative but untraceable and not a
    classified monoid.  Big columnar input, r > mesh: must stream via
    host-combined spill runs, with exact parity."""
    from dpark_tpu import Columns
    n = 16000
    i = np.arange(n, dtype=np.int64)
    keys = (i * 7) % 97
    vals = (i % 5 + 1) * 6
    got = dict(tctx.parallelize(Columns(keys, vals), 8)
               .reduceByKey(math.gcd, 24).collect())
    assert got == _expect_gcd(keys, vals)
    stores = tctx.scheduler.executor.shuffle_store
    assert any(s.get("host_combine") for s in stores.values()), \
        "untraceable merge did not take the spilled-run stream"


def test_untraceable_merge_streams_r_le_mesh(tctx, small_chunks):
    from dpark_tpu import Columns
    n = 12000
    i = np.arange(n, dtype=np.int64)
    keys = i % 53
    vals = (i % 7 + 1) * 10
    got = dict(tctx.parallelize(Columns(keys, vals), 8)
               .reduceByKey(math.gcd, 4).collect())
    assert got == _expect_gcd(keys, vals)
    stores = tctx.scheduler.executor.shuffle_store
    assert any(s.get("host_combine") for s in stores.values())


def test_untraceable_merge_small_stays_in_core(tctx):
    """Small inputs keep the in-core path (no spill directory)."""
    from dpark_tpu import Columns
    i = np.arange(400, dtype=np.int64)
    got = dict(tctx.parallelize(Columns(i % 11, i % 3 + 1), 8)
               .reduceByKey(math.gcd, 4).collect())
    assert got == _expect_gcd(i % 11, i % 3 + 1)
    stores = tctx.scheduler.executor.shuffle_store
    assert not any(s.get("host_combine") for s in stores.values())


def test_untraceable_merge_streams_text(tctx, small_chunks, tmp_path):
    """Text source + untraceable merge: host prologue feeds the same
    spilled stream (create runs device-side, merge folds at export)."""
    p = str(tmp_path / "nums.txt")
    with open(p, "w") as f:
        for i in range(6000):
            f.write("%d %d\n" % (i % 41, (i % 6 + 1) * 4))

    def parse(line):
        a, b = line.split()
        return (int(a), int(b))

    got = dict(tctx.textFile(p, splitSize=4000)
               .map(parse)
               .reduceByKey(math.gcd, 16).collect())

    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    expect = dict(lctx.textFile(p, splitSize=4000)
                  .map(parse)
                  .reduceByKey(math.gcd, 16).collect())
    lctx.stop()
    assert got == expect


def test_untraceable_merge_downstream_group(tctx, small_chunks):
    """The export feeds downstream host stages: count over the reduced
    RDD and a join against it."""
    from dpark_tpu import Columns
    n = 8000
    i = np.arange(n, dtype=np.int64)
    keys = i % 37
    vals = (i % 4 + 1) * 9
    r = tctx.parallelize(Columns(keys, vals), 8).reduceByKey(
        math.gcd, 16)
    assert r.count() == 37
    expect = _expect_gcd(keys, vals)
    top = dict(r.filter(lambda kv: kv[0] < 5).collect())
    assert top == {k: v for k, v in expect.items() if k < 5}
