"""Device ingest for text sources (SURVEY.md 3.1 hot loop #1): the narrow
chain over ctx.textFile runs as a host prologue (user generators or the
verified C++ tokenizer), string keys dictionary-encode to int64 columns,
and the shuffle+combine ride the device.  Every test asserts parity with
the local master."""

import gzip
import os

import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def corpus(tmp_path):
    import random
    rng = random.Random(42)
    words = ["spark", "tpu", "mesh", "jit", "pallas", "ici", "hbm"]
    p = str(tmp_path / "corpus.txt")
    with open(p, "w") as f:
        for _ in range(4000):
            f.write(" ".join(rng.choices(words, k=5)) + "\n")
    return p


def _local_counts(path, **kw):
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    got = dict(lctx.textFile(path, **kw)
               .flatMap(lambda line: line.split())
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 4).collect())
    lctx.stop()
    return got


def _text_path_used(tctx):
    ex = tctx.scheduler.executor
    return bool(ex.shuffle_store) and hasattr(ex, "token_dict")


def test_canonical_wordcount_rides_device(tctx, corpus):
    got = dict(tctx.textFile(corpus, splitSize=30000)
               .flatMap(lambda line: line.split())
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 4).collect())
    assert got == _local_counts(corpus, splitSize=30000)
    assert _text_path_used(tctx)


def test_str_split_method_ref(tctx, corpus):
    got = dict(tctx.textFile(corpus).flatMap(str.split)
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 4).collect())
    assert got == _local_counts(corpus)


def test_non_canonical_chain_host_prologue(tctx, corpus):
    """Arbitrary string-keyed narrow chain: the user's own generators
    run per split, keys encode, the device combines."""
    def first_two(line):
        return [(w[:2], len(w)) for w in line.split()]

    def run(ctx):
        return dict(ctx.textFile(corpus)
                    .flatMap(first_two)
                    .reduceByKey(lambda a, b: a + b, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert _text_path_used(tctx)


def test_int_key_text_chain_no_encoding(tctx, tmp_path):
    p = str(tmp_path / "nums.txt")
    with open(p, "w") as f:
        for i in range(2000):
            f.write("%d\n" % i)

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=4000)
                    .map(lambda l: (int(l) % 13, 1))
                    .reduceByKey(lambda a, b: a + b, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert tctx.scheduler.executor.shuffle_store


def test_group_by_key_words(tctx, corpus):
    def run(ctx):
        return {k: sorted(v) for k, v in
                ctx.textFile(corpus)
                .flatMap(lambda line: line.split())
                .map(lambda w: (w, len(w)))
                .groupByKey(4).collect()}

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_downstream_map_after_reduce(tctx, corpus):
    """Further ops on the reduced words force the host path for the
    result stage; the export bridge must hand it DECODED rows."""
    def run(ctx):
        return sorted(ctx.textFile(corpus)
                      .flatMap(lambda line: line.split())
                      .map(lambda w: (w, 1))
                      .reduceByKey(lambda a, b: a + b, 4)
                      .map(lambda kv: (kv[0].upper(), kv[1] * 2))
                      .collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_word_join_device(tctx, corpus):
    """Str-keyed join: both sides encode through one dict, the device
    matches ids, the exit decodes."""
    def run(ctx):
        words = ctx.textFile(corpus).flatMap(lambda line: line.split())
        a = words.map(lambda w: (w, 1)).reduceByKey(
            lambda x, y: x + y, 4)
        b = words.map(lambda w: (w, len(w))).reduceByKey(
            lambda x, y: x, 4)
        return sorted(a.join(b, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_unicode_whitespace_falls_back_correctly(tctx, tmp_path):
    """NBSP splits in Python but not in the byte tokenizer: the sample
    verification must catch the divergence and take the host prologue —
    results stay correct."""
    p = str(tmp_path / "nbsp.txt")
    with open(p, "w", encoding="utf-8") as f:
        for i in range(200):
            f.write("a\u00a0b c%d\n" % (i % 3))

    def run(ctx):
        return dict(ctx.textFile(p)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert "a" in got and "b" in got     # NBSP split like Python
    assert "a\u00a0b" not in got


def test_late_split_divergence_caught(tctx, tmp_path):
    """ADVICE r2: divergence appearing AFTER the first split's 4KB
    sample — NBSP and \\x1c (both str.split() whitespace, neither byte-
    tokenizer whitespace) only in later splits — must not silently
    corrupt counts: the per-split byte-safety scan routes exactly those
    splits to the host prologue."""
    p = str(tmp_path / "late.txt")
    with open(p, "w", encoding="utf-8", newline="") as f:
        for i in range(2000):
            f.write("clean ascii words %d\n" % (i % 5))  # ~40KB clean
        for i in range(200):
            f.write("a b\n")                # unicode whitespace
        for i in range(200):
            f.write("p\x1cq\n")                  # FS control char

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=8000)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert got["a"] == 200 and got["b"] == 200   # NBSP split
    assert got["p"] == 200 and got["q"] == 200   # \x1c split
    assert "a b" not in got and "p\x1cq" not in got
    assert got["clean"] == 2000                  # clean splits rode C++


def test_long_first_line_not_trusted(tctx, tmp_path):
    """A >4KB first line leaves nothing to verify the byte tokenizer
    against; the canonical path must NOT run unverified."""
    p = str(tmp_path / "long.txt")
    with open(p, "w", encoding="utf-8") as f:
        f.write("x y " * 2000 + "\n")     # NBSP inside, one line

    def run(ctx):
        return dict(ctx.textFile(p)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 2).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert "x" in got and "y" in got     # NBSP split like Python
    assert "x\u00a0y" not in got


def test_separator_split_rides_device(tctx, tmp_path):
    """flatMap(lambda l: l.split('\\t')) + (w,1): the constant-
    separator C++ tokenizer (VERDICT r2 ask #9's 'one more native
    tokenizer shape').  Exact str.split(sep) semantics incl. EMPTY
    fields between consecutive separators."""
    p = str(tmp_path / "tsv.txt")
    with open(p, "w") as f:
        for i in range(3000):
            f.write("a\tb b\t\tc%d\n" % (i % 4))   # empty field + space
            if i % 7 == 0:
                f.write("\n")                       # empty line -> ['']

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=9000)
                    .flatMap(lambda line: line.split("\t"))
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert got["b b"] == 3000          # space is NOT a separator here
    assert got[""] == 3000 + (3000 + 6) // 7   # empties counted
    assert _text_path_used(tctx)


def test_separator_split_comma(tctx, tmp_path):
    p = str(tmp_path / "c.txt")
    with open(p, "w") as f:
        for i in range(2000):
            f.write("x,y%d,,z\n" % (i % 3))

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=7000)
                    .flatMap(lambda line: line.split(","))
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect and got[""] == 2000


def test_parallel_ingest_matches_serial(tmp_path):
    """VERDICT r2 ask #2: splits tokenize concurrently into private
    dicts merged in split order — results AND the global id assignment
    must be identical to the serial walk."""
    import random
    import dpark_tpu.conf as conf
    from dpark_tpu import DparkContext
    rng = random.Random(3)
    words = ["w%d" % i for i in range(300)]
    p = str(tmp_path / "par.txt")
    with open(p, "w") as f:
        for _ in range(3000):
            f.write(" ".join(rng.choices(words, k=6)) + "\n")

    def run(threads):
        was = conf.INGEST_THREADS
        conf.INGEST_THREADS = threads
        try:
            c = DparkContext("tpu")
            c.start()
            got = dict(c.textFile(p, splitSize=9000)
                       .flatMap(lambda line: line.split())
                       .map(lambda w: (w, 1))
                       .reduceByKey(lambda a, b: a + b, 4).collect())
            td = c.scheduler.executor.token_dict
            vocab = [td.decode(i) for i in range(len(td))]
            c.stop()
            return got, vocab
        finally:
            conf.INGEST_THREADS = was

    serial, vocab_serial = run(1)
    parallel, vocab_parallel = run(4)
    assert parallel == serial
    assert vocab_parallel == vocab_serial    # id-for-id identical


def test_parallel_ingest_unsafe_first_split(tmp_path):
    """The sample verification may not resolve on split 0 (unsafe
    prefix): the parallel path must keep walking serially until it
    does — the C++ tokenizer never runs unverified, and parity holds
    with the divergent bytes in the FIRST split this time."""
    import dpark_tpu.conf as conf
    from dpark_tpu import DparkContext
    p = str(tmp_path / "front.txt")
    with open(p, "w", encoding="utf-8") as f:
        for i in range(500):
            f.write("x y%d\n" % (i % 7))  # NBSP up front
        for i in range(3000):
            f.write("clean words here %d\n" % (i % 5))

    def run(threads, master):
        was = conf.INGEST_THREADS
        conf.INGEST_THREADS = threads
        try:
            c = DparkContext(master)
            c.start()
            got = dict(c.textFile(p, splitSize=7000)
                       .flatMap(lambda line: line.split())
                       .map(lambda w: (w, 1))
                       .reduceByKey(lambda a, b: a + b, 4).collect())
            c.stop()
            return got
        finally:
            conf.INGEST_THREADS = was

    expect = run(1, "local")
    got = run(4, "tpu")
    assert got == expect
    assert got["x"] == 500 and "x y0" not in got


def test_gzip_source_host_prologue(tctx, tmp_path):
    p = str(tmp_path / "z.gz")
    with gzip.open(p, "wt") as f:
        for i in range(500):
            f.write("x y z w%d\n" % (i % 5))

    def run(ctx):
        return dict(ctx.textFile(p)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 2).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_cache_not_poisoned_by_encoded_results(tctx, corpus):
    """A cached reduced-words RDD must return strings on every access."""
    r = (tctx.textFile(corpus)
         .flatMap(lambda line: line.split())
         .map(lambda w: (w, 1))
         .reduceByKey(lambda a, b: a + b, 4).cache())
    first = dict(r.collect())
    second = dict(r.collect())
    assert first == second
    assert all(isinstance(k, str) for k in second)


def test_lineage_recovery_after_hbm_eviction(tctx, corpus):
    """Evicting the encoded shuffle recomputes the text stage through
    lineage; decoded results stay identical."""
    r = (tctx.textFile(corpus)
         .flatMap(lambda line: line.split())
         .map(lambda w: (w, 1))
         .reduceByKey(lambda a, b: a + b, 4))
    first = dict(r.collect())
    ex = tctx.scheduler.executor
    for sid in list(ex.shuffle_store):
        ex.drop_shuffle(sid)
    assert dict(r.collect()) == first


def test_tabular_source_rides_device(tctx, tmp_path):
    """Tabular chains reach the device shuffle via the host prologue."""
    from dpark_tpu import DparkContext
    from dpark_tpu.tabular import write_tabular
    p = str(tmp_path / "t.tab")
    rows = [(i % 23, i % 7, i) for i in range(4000)]
    write_tabular(p, ["k", "v", "x"], rows, chunk_rows=500)

    def run(ctx):
        return dict(ctx.tabular(p)
                    .map(lambda r: (r[0], r[1]))
                    .reduceByKey(lambda a, b: a + b, 4).collect())

    got = run(tctx)
    assert tctx.scheduler.executor.shuffle_store, "host fallback"
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
