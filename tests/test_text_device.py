"""Device ingest for text sources (SURVEY.md 3.1 hot loop #1): the narrow
chain over ctx.textFile runs as a host prologue (user generators or the
verified C++ tokenizer), string keys dictionary-encode to int64 columns,
and the shuffle+combine ride the device.  Every test asserts parity with
the local master."""

import gzip
import os

import pytest


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def corpus(tmp_path):
    import random
    rng = random.Random(42)
    words = ["spark", "tpu", "mesh", "jit", "pallas", "ici", "hbm"]
    p = str(tmp_path / "corpus.txt")
    with open(p, "w") as f:
        for _ in range(4000):
            f.write(" ".join(rng.choices(words, k=5)) + "\n")
    return p


def _local_counts(path, **kw):
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    got = dict(lctx.textFile(path, **kw)
               .flatMap(lambda line: line.split())
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 4).collect())
    lctx.stop()
    return got


def _text_path_used(tctx):
    ex = tctx.scheduler.executor
    return bool(ex.shuffle_store) and hasattr(ex, "token_dict")


def test_canonical_wordcount_rides_device(tctx, corpus):
    got = dict(tctx.textFile(corpus, splitSize=30000)
               .flatMap(lambda line: line.split())
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 4).collect())
    assert got == _local_counts(corpus, splitSize=30000)
    assert _text_path_used(tctx)


def test_str_split_method_ref(tctx, corpus):
    got = dict(tctx.textFile(corpus).flatMap(str.split)
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 4).collect())
    assert got == _local_counts(corpus)


def test_non_canonical_chain_host_prologue(tctx, corpus):
    """Arbitrary string-keyed narrow chain: the user's own generators
    run per split, keys encode, the device combines."""
    def first_two(line):
        return [(w[:2], len(w)) for w in line.split()]

    def run(ctx):
        return dict(ctx.textFile(corpus)
                    .flatMap(first_two)
                    .reduceByKey(lambda a, b: a + b, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert _text_path_used(tctx)


def test_int_key_text_chain_no_encoding(tctx, tmp_path):
    p = str(tmp_path / "nums.txt")
    with open(p, "w") as f:
        for i in range(2000):
            f.write("%d\n" % i)

    def run(ctx):
        return dict(ctx.textFile(p, splitSize=4000)
                    .map(lambda l: (int(l) % 13, 1))
                    .reduceByKey(lambda a, b: a + b, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert tctx.scheduler.executor.shuffle_store


def test_group_by_key_words(tctx, corpus):
    def run(ctx):
        return {k: sorted(v) for k, v in
                ctx.textFile(corpus)
                .flatMap(lambda line: line.split())
                .map(lambda w: (w, len(w)))
                .groupByKey(4).collect()}

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_downstream_map_after_reduce(tctx, corpus):
    """Further ops on the reduced words force the host path for the
    result stage; the export bridge must hand it DECODED rows."""
    def run(ctx):
        return sorted(ctx.textFile(corpus)
                      .flatMap(lambda line: line.split())
                      .map(lambda w: (w, 1))
                      .reduceByKey(lambda a, b: a + b, 4)
                      .map(lambda kv: (kv[0].upper(), kv[1] * 2))
                      .collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_word_join_device(tctx, corpus):
    """Str-keyed join: both sides encode through one dict, the device
    matches ids, the exit decodes."""
    def run(ctx):
        words = ctx.textFile(corpus).flatMap(lambda line: line.split())
        a = words.map(lambda w: (w, 1)).reduceByKey(
            lambda x, y: x + y, 4)
        b = words.map(lambda w: (w, len(w))).reduceByKey(
            lambda x, y: x, 4)
        return sorted(a.join(b, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_unicode_whitespace_falls_back_correctly(tctx, tmp_path):
    """NBSP splits in Python but not in the byte tokenizer: the sample
    verification must catch the divergence and take the host prologue —
    results stay correct."""
    p = str(tmp_path / "nbsp.txt")
    with open(p, "w", encoding="utf-8") as f:
        for i in range(200):
            f.write("a\u00a0b c%d\n" % (i % 3))

    def run(ctx):
        return dict(ctx.textFile(p)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 4).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert "a" in got and "b" in got     # NBSP split like Python
    assert "a\u00a0b" not in got


def test_long_first_line_not_trusted(tctx, tmp_path):
    """A >4KB first line leaves nothing to verify the byte tokenizer
    against; the canonical path must NOT run unverified."""
    p = str(tmp_path / "long.txt")
    with open(p, "w", encoding="utf-8") as f:
        f.write("x y " * 2000 + "\n")     # NBSP inside, one line

    def run(ctx):
        return dict(ctx.textFile(p)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 2).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
    assert "x" in got and "y" in got     # NBSP split like Python
    assert "x\u00a0y" not in got


def test_gzip_source_host_prologue(tctx, tmp_path):
    p = str(tmp_path / "z.gz")
    with gzip.open(p, "wt") as f:
        for i in range(500):
            f.write("x y z w%d\n" % (i % 5))

    def run(ctx):
        return dict(ctx.textFile(p)
                    .flatMap(lambda line: line.split())
                    .map(lambda w: (w, 1))
                    .reduceByKey(lambda x, y: x + y, 2).collect())

    from dpark_tpu import DparkContext
    got = run(tctx)
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect


def test_cache_not_poisoned_by_encoded_results(tctx, corpus):
    """A cached reduced-words RDD must return strings on every access."""
    r = (tctx.textFile(corpus)
         .flatMap(lambda line: line.split())
         .map(lambda w: (w, 1))
         .reduceByKey(lambda a, b: a + b, 4).cache())
    first = dict(r.collect())
    second = dict(r.collect())
    assert first == second
    assert all(isinstance(k, str) for k in second)


def test_lineage_recovery_after_hbm_eviction(tctx, corpus):
    """Evicting the encoded shuffle recomputes the text stage through
    lineage; decoded results stay identical."""
    r = (tctx.textFile(corpus)
         .flatMap(lambda line: line.split())
         .map(lambda w: (w, 1))
         .reduceByKey(lambda a, b: a + b, 4))
    first = dict(r.collect())
    ex = tctx.scheduler.executor
    for sid in list(ex.shuffle_store):
        ex.drop_shuffle(sid)
    assert dict(r.collect()) == first


def test_tabular_source_rides_device(tctx, tmp_path):
    """Tabular chains reach the device shuffle via the host prologue."""
    from dpark_tpu import DparkContext
    from dpark_tpu.tabular import write_tabular
    p = str(tmp_path / "t.tab")
    rows = [(i % 23, i % 7, i) for i in range(4000)]
    write_tabular(p, ["k", "v", "x"], rows, chunk_rows=500)

    def run(ctx):
        return dict(ctx.tabular(p)
                    .map(lambda r: (r[0], r[1]))
                    .reduceByKey(lambda a, b: a + b, 4).collect())

    got = run(tctx)
    assert tctx.scheduler.executor.shuffle_store, "host fallback"
    lctx = DparkContext("local")
    expect = run(lctx)
    lctx.stop()
    assert got == expect
