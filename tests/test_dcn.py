"""DCN data plane (SURVEY.md section 2.8): TCP bucket server + chunked
broadcast fetch + tracker metadata plane, exercised across real process
boundaries — two ranks with SEPARATE workdirs exchange shuffle data and
broadcast values over the network path, and distributed.py bootstraps a
2-process jax world."""

import os
import pickle
import subprocess
import sys
import textwrap
import time

import pytest


def test_bucket_server_roundtrip(tmp_path):
    """In-process: bucket files written in one workdir are served over
    TCP and read through the ordinary read_bucket protocol."""
    from dpark_tpu.dcn import BucketServer
    from dpark_tpu.shuffle import LocalFileShuffle, read_bucket
    wd = str(tmp_path / "wd0")
    os.makedirs(wd)
    # write bucket files directly against the explicit workdir
    for rid, items in enumerate([[("a", [1])], [("b", [2, 3])]]):
        path = LocalFileShuffle.get_output_file(7, 0, rid, workdir=wd)
        from dpark_tpu.utils import atomic_file, compress
        with atomic_file(path) as f:
            f.write(compress(pickle.dumps(items, -1)))
    srv = BucketServer(wd).start()
    try:
        assert read_bucket(srv.addr, 7, 0, 0) == [("a", [1])]
        assert read_bucket(srv.addr, 7, 0, 1) == [("b", [2, 3])]
        with pytest.raises(Exception):
            read_bucket(srv.addr, 7, 0, 9)       # missing bucket
    finally:
        srv.stop()


def test_request_framing_is_not_pickle(tmp_path):
    """Security (ADVICE r2): the server must never unpickle network
    input.  A crafted pickle sent as a request frame must not execute —
    it is rejected as a malformed frame (connection closed, no
    response), and with DPARK_DCN_SECRET set, frames without a valid
    MAC are likewise dropped."""
    import socket
    import struct as struct_mod
    from dpark_tpu.dcn import BucketServer, fetch

    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    srv = BucketServer(wd, host="127.0.0.1").start()
    host, port = srv.bind_address
    try:
        # a pickle that would touch the filesystem if unpickled
        evil = pickle.dumps(("bucket", 1, 0, 0))
        marker = tmp_path / "pwned"

        class Evil:
            def __reduce__(self):
                return (open, (str(marker), "w"))
        evil = pickle.dumps(Evil())
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(struct_mod.pack("!I", len(evil)) + evil)
            # server hangs up without answering
            s.settimeout(5)
            assert s.recv(1) == b""
        assert not marker.exists()

        # with a shared secret, an un-MACed (but well-formed JSON)
        # request is also dropped...
        os.environ["DPARK_DCN_SECRET"] = "s3cret"
        try:
            blob = b'["bcast_meta",1]'
            with socket.create_connection((host, port),
                                          timeout=5) as s:
                s.sendall(struct_mod.pack("!I", len(blob)) + blob)
                s.settimeout(5)
                assert s.recv(1) == b""
            # ...while the authenticated client path still works
            with pytest.raises(IOError):
                fetch("tcp://%s:%d" % (host, port),
                      ("bcast_meta", 999))     # valid MAC, missing id
        finally:
            del os.environ["DPARK_DCN_SECRET"]
    finally:
        srv.stop()


_RANK_SCRIPT = textwrap.dedent("""
    import os, pickle, sys, time
    rank = int(sys.argv[1])
    workdir = sys.argv[2]
    tracker_addr = sys.argv[3]
    coord = sys.argv[4]

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from dpark_tpu import distributed
    pid, n = distributed.init(coordinator_address=coord,
                              num_processes=2, process_id=rank)
    assert n == 2 and jax.process_count() == 2, \\
        (n, jax.process_count())

    from dpark_tpu.env import env
    env.start(is_master=(rank == 0),
              environ={"DPARK_WORKDIR": workdir,
                       "DPARK_BUCKET_SERVER": "1"})
    from dpark_tpu.broadcast import Broadcast
    from dpark_tpu.shuffle import LocalFileShuffle, read_bucket
    from dpark_tpu.tracker import TrackerClient
    t = TrackerClient(tracker_addr)

    # each rank writes one map output (2 reduce partitions) and
    # advertises its own tcp:// server uri through the tracker
    buckets = [[("k%d" % rank, [rank])], [("x%d" % rank, [10 + rank])]]
    uri = LocalFileShuffle.write_buckets(3, rank, buckets)
    assert uri.startswith("tcp://"), uri
    t.set("uri%d" % rank, uri)

    if rank == 0:
        big = {"payload": list(range(400000))}      # multi-chunk
        t.set("bcast", pickle.dumps(Broadcast(big), -1))

    other = 1 - rank
    for _ in range(200):
        peer = t.get("uri%d" % other)
        if peer:
            break
        time.sleep(0.05)
    assert peer and peer != uri

    # cross-process shuffle fetch over TCP
    got0 = read_bucket(peer, 3, other, 0)
    got1 = read_bucket(peer, 3, other, 1)
    assert got0 == [("k%d" % other, [other])], got0
    assert got1 == [("x%d" % other, [10 + other])], got1

    if rank == 1:
        # remote chunked broadcast fetch (different workdir: the local
        # file path does not exist here)
        for _ in range(200):
            blob = t.get("bcast")
            if blob:
                break
            time.sleep(0.05)
        b = pickle.loads(blob)
        assert b.value == {"payload": list(range(400000))}
        # the remote fetch caches chunks locally for co-located workers
        assert os.path.exists(os.path.join(
            workdir, "broadcast", "b%d.meta" % b.bid))
        t.set("rank1_done", "ok")
    else:
        for _ in range(600):
            if t.get("rank1_done") == "ok":
                break
            time.sleep(0.05)
        assert t.get("rank1_done") == "ok"
    print("RANK%d_OK" % rank, flush=True)
""")


def test_two_rank_exchange_over_tcp(tmp_path):
    """Two ranks, separate workdirs: distributed.py bootstrap, shuffle
    buckets exchanged over the TCP data plane, multi-chunk broadcast
    fetched remotely."""
    from dpark_tpu.tracker import TrackerServer
    srv = TrackerServer()
    srv.start()
    try:
        # file:// rendezvous: rank 0 picks the port itself (the racy
        # bind/close/reuse pattern was ADVICE r2 finding #4)
        coord = "file://" + str(tmp_path / "coord.addr")
        script = str(tmp_path / "rank.py")
        with open(script, "w") as f:
            f.write(_RANK_SCRIPT)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        child_env = dict(os.environ)
        child_env["PYTHONPATH"] = repo_root + os.pathsep + \
            child_env.get("PYTHONPATH", "")
        procs = []
        for rank in (0, 1):
            wd = str(tmp_path / ("wd%d" % rank))
            os.makedirs(wd, exist_ok=True)
            procs.append(subprocess.Popen(
                [sys.executable, script, str(rank), wd,
                 srv.addr, coord],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=child_env))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out)
            assert p.returncode == 0, "rank %d:\n%s" % (rank, out)
            assert ("RANK%d_OK" % rank) in out, out
    finally:
        srv.stop()
