"""DCN data plane (SURVEY.md section 2.8): TCP bucket server + chunked
broadcast fetch + tracker metadata plane, exercised across real process
boundaries — two ranks with SEPARATE workdirs exchange shuffle data and
broadcast values over the network path, and distributed.py bootstraps a
2-process jax world."""

import os
import pickle
import subprocess
import sys
import textwrap
import time

import pytest


def test_bucket_server_roundtrip(tmp_path):
    """In-process: bucket files written in one workdir are served over
    TCP and read through the ordinary read_bucket protocol."""
    from dpark_tpu.dcn import BucketServer
    from dpark_tpu.shuffle import LocalFileShuffle, read_bucket
    wd = str(tmp_path / "wd0")
    os.makedirs(wd)
    # write bucket files directly against the explicit workdir
    for rid, items in enumerate([[("a", [1])], [("b", [2, 3])]]):
        path = LocalFileShuffle.get_output_file(7, 0, rid, workdir=wd)
        from dpark_tpu.utils import atomic_file, compress
        with atomic_file(path) as f:
            f.write(compress(pickle.dumps(items, -1)))
    srv = BucketServer(wd).start()
    try:
        assert read_bucket(srv.addr, 7, 0, 0) == [("a", [1])]
        assert read_bucket(srv.addr, 7, 0, 1) == [("b", [2, 3])]
        with pytest.raises(Exception):
            read_bucket(srv.addr, 7, 0, 9)       # missing bucket
    finally:
        srv.stop()


def test_request_framing_is_not_pickle(tmp_path):
    """Security (ADVICE r2): the server must never unpickle network
    input.  A crafted pickle sent as a request frame must not execute —
    it is rejected as a malformed frame (connection closed, no
    response), and with DPARK_DCN_SECRET set, frames without a valid
    MAC are likewise dropped."""
    import socket
    import struct as struct_mod
    from dpark_tpu.dcn import BucketServer, fetch

    wd = str(tmp_path / "wd")
    os.makedirs(wd)
    srv = BucketServer(wd, host="127.0.0.1").start()
    host, port = srv.bind_address
    try:
        # a pickle that would touch the filesystem if unpickled
        evil = pickle.dumps(("bucket", 1, 0, 0))
        marker = tmp_path / "pwned"

        class Evil:
            def __reduce__(self):
                return (open, (str(marker), "w"))
        evil = pickle.dumps(Evil())
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(struct_mod.pack("!I", len(evil)) + evil)
            # server hangs up without answering
            s.settimeout(5)
            assert s.recv(1) == b""
        assert not marker.exists()

        # with a shared secret, an un-MACed (but well-formed JSON)
        # request is also dropped...
        os.environ["DPARK_DCN_SECRET"] = "s3cret"
        try:
            blob = b'["bcast_meta",1]'
            with socket.create_connection((host, port),
                                          timeout=5) as s:
                s.sendall(struct_mod.pack("!I", len(blob)) + blob)
                s.settimeout(5)
                assert s.recv(1) == b""
            # ...while the authenticated client path still works
            with pytest.raises(IOError):
                fetch("tcp://%s:%d" % (host, port),
                      ("bcast_meta", 999))     # valid MAC, missing id
        finally:
            del os.environ["DPARK_DCN_SECRET"]
    finally:
        srv.stop()


# the two-rank exchange script is owned by __graft_entry__ (the dry run
# executes it on deployment hosts, where tests/ may not ship)
def _rank_script():
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_graft_entry_for_test", os.path.join(root,
                                              "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.DCN_RANK_SCRIPT


_P2P_SCRIPT = textwrap.dedent("""
    import os, pickle, sys, time
    rank = int(sys.argv[1])
    workdir = sys.argv[2]
    tracker_addr = sys.argv[3]

    from dpark_tpu.env import env
    env.start(is_master=(rank == 0),
              environ={"DPARK_WORKDIR": workdir,
                       "DPARK_BUCKET_SERVER": "1",
                       "DPARK_TRACKER": tracker_addr})
    from dpark_tpu.broadcast import Broadcast
    t = env.tracker_client

    if rank == 0:
        big = {"payload": list(range(1200000))}      # several chunks
        b = Broadcast(big)
        t.set("handle", pickle.dumps(b, -1))
        # serve until both fetchers confirm, then report serve counts
        for _ in range(600):
            if t.get("done1") and t.get("done2"):
                break
            time.sleep(0.05)
        counts = env.bucket_server.bcast_serves
        print("ORIGIN_SERVES %d %d"
              % (len(counts), max(counts.values(), default=0)),
              flush=True)
    else:
        # rank 2 waits for rank 1 so the holder set has grown before
        # its fetch (deterministic: its chunks must all come from r1)
        if rank == 2:
            for _ in range(600):
                if t.get("done1"):
                    break
                time.sleep(0.05)
            assert t.get("done1") == "ok"
        for _ in range(600):
            blob = t.get("handle")
            if blob:
                break
            time.sleep(0.05)
        b = pickle.loads(blob)
        assert b.value["payload"][-1] == 1199999
        t.set("done%d" % rank, "ok")
        # every fetched chunk is now re-served by this rank: its uri
        # must appear in the holder set
        my_uri = env.bucket_server.addr
        holders0 = t.get("bcast:%d:0" % b.bid) or []
        assert my_uri in holders0, (my_uri, holders0)
        if rank == 1:
            # keep serving until rank 2 has fetched (a fetcher exiting
            # early just falls back to the origin — correct, but this
            # test pins the P2P path itself)
            for _ in range(600):
                if t.get("done2"):
                    break
                time.sleep(0.05)
    print("RANK%d_OK" % rank, flush=True)
""")


def test_three_rank_p2p_broadcast(tmp_path):
    """P2P fan-out (the reference's tree/P2P broadcast mechanism):
    rank 1 fetches from the origin and registers as a holder; rank 2's
    fetch must then come from rank 1, so the ORIGIN serves each chunk
    at most once, and the holder set has grown to all three ranks."""
    from dpark_tpu.tracker import TrackerServer, TrackerClient
    srv = TrackerServer()
    srv.start()
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = repo_root + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    script = str(tmp_path / "p2p.py")
    with open(script, "w") as f:
        f.write(_P2P_SCRIPT)
    try:
        procs = []
        for rank in (0, 1, 2):
            wd = str(tmp_path / ("wd%d" % rank))
            os.makedirs(wd, exist_ok=True)
            procs.append(subprocess.Popen(
                [sys.executable, script, str(rank), wd, srv.addr],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=child_env))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out)
            assert p.returncode == 0, "rank %d:\n%s" % (rank, out)
            assert ("RANK%d_OK" % rank) in out, out
        # origin served every chunk at most ONCE (rank 1's fetch);
        # rank 2 was fed entirely by rank 1
        for line in outs[0].splitlines():
            if line.startswith("ORIGIN_SERVES "):
                nserved, maxserves = map(int, line.split()[1:])
                assert maxserves <= 1, line
                assert nserved >= 1, line
                break
        else:
            raise AssertionError("no ORIGIN_SERVES line:\n%s" % outs[0])
        # the holder set grew to both fetchers (the origin is an
        # implicit holder known from the handle, not registered)
        cli = TrackerClient(srv.addr)
        holders = cli.get("bcast:1:0")
        assert holders is not None and len(set(holders)) == 2, holders
        cli.close()
    finally:
        srv.stop()


def test_rendezvous_rejects_stale_accepts_fresh(tmp_path):
    """A dead LEFTOVER coordinator file is never joined; rank 0's fresh
    publish (identity change) is."""
    import threading
    import time
    from dpark_tpu.distributed import _file_rendezvous
    path = str(tmp_path / "coord")
    with open(path, "w") as f:
        f.write("127.0.0.1:1")                  # dead leftover
    os.utime(path, (time.time() - 3600,) * 2)
    got = {}

    def rank1():
        got["addr"] = _file_rendezvous(path, 1, timeout=30)

    t = threading.Thread(target=rank1)
    t.start()
    time.sleep(0.5)                  # rank 1 snapshots the leftover
    addr0 = _file_rendezvous(path, 0)
    t.join(30)
    assert got["addr"] == addr0 != "127.0.0.1:1"


def test_rendezvous_accepts_old_but_alive_address(tmp_path):
    """A rank that starts long after rank 0 published (old mtime, no
    identity change) must still join once the coordinator is LIVE —
    the round-3 review found the old wall-clock freshness window
    rejected exactly this."""
    import socket
    import time
    from dpark_tpu.distributed import _file_rendezvous
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    addr = "127.0.0.1:%d" % srv.getsockname()[1]
    path = str(tmp_path / "coord")
    with open(path, "w") as f:
        f.write(addr)
    os.utime(path, (time.time() - 3600,) * 2)   # published "long ago"
    try:
        assert _file_rendezvous(path, 3, timeout=30) == addr
    finally:
        srv.close()


def test_two_rank_exchange_over_tcp(tmp_path):
    """Two ranks, separate workdirs: distributed.py bootstrap, shuffle
    buckets exchanged over the TCP data plane, multi-chunk broadcast
    fetched remotely."""
    from dpark_tpu.tracker import TrackerServer
    srv = TrackerServer()
    srv.start()
    try:
        # file:// rendezvous: rank 0 picks the port itself (the racy
        # bind/close/reuse pattern was ADVICE r2 finding #4)
        coord = "file://" + str(tmp_path / "coord.addr")
        script = str(tmp_path / "rank.py")
        with open(script, "w") as f:
            f.write(_rank_script())
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        child_env = dict(os.environ)
        child_env["PYTHONPATH"] = repo_root + os.pathsep + \
            child_env.get("PYTHONPATH", "")
        procs = []
        for rank in (0, 1):
            wd = str(tmp_path / ("wd%d" % rank))
            os.makedirs(wd, exist_ok=True)
            procs.append(subprocess.Popen(
                [sys.executable, script, str(rank), wd,
                 srv.addr, coord],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=child_env))
        outs = []
        for rank, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out)
            assert p.returncode == 0, "rank %d:\n%s" % (rank, out)
            assert ("RANK%d_OK" % rank) in out, out
    finally:
        srv.stop()


def test_blacklist_reroutes_replica_fetch(tmp_path, monkeypatch):
    """hostatus ALTERS AN OUTCOME (VERDICT r4 #6): a map output served
    by two replicas — one on a dead host — keeps fetching correctly,
    the dead host accumulates failures until blacklisted, and a
    blacklisted replica is no longer even attempted (the bytes REROUTE,
    first-listed or not)."""
    from dpark_tpu import shuffle as shuffle_mod
    from dpark_tpu.dcn import BucketServer
    from dpark_tpu.env import env
    from dpark_tpu.shuffle import (LocalFileShuffle,
                                   SimpleShuffleFetcher, uri_host)
    from dpark_tpu.utils import atomic_file, compress

    wd = str(tmp_path / "live")
    os.makedirs(wd)
    sid = 71
    items = [("k", [5]), ("j", [7])]
    path = LocalFileShuffle.get_output_file(sid, 0, 0, workdir=wd)
    with atomic_file(path) as f:
        f.write(compress(pickle.dumps(items, -1)))
    live = BucketServer(wd).start()
    dead_uri = "tcp://127.0.0.9:1"       # nothing listens: refused
    dead_host = uri_host(dead_uri)
    try:
        # dead replica listed FIRST: without health ranking it would be
        # attempted every time
        env.map_output_tracker.register_outputs(
            sid, [[dead_uri, live.addr]])
        f = SimpleShuffleFetcher()
        got = []
        f.fetch(sid, 0, got.extend)
        assert got == items              # reroute, correct data
        # after that one failure the dead host ranks last, so healthy
        # fetches never touch it again — blacklisting needs the
        # FetchFailed retry path: a shuffle whose ONLY location is the
        # dead host fails per attempt, exactly like scheduler retries
        env.map_output_tracker.register_outputs(71019, [dead_uri])
        from dpark_tpu.shuffle import FetchFailed
        for _ in range(2):
            with pytest.raises(FetchFailed):
                f.fetch(71019, 0, lambda items: None)
        assert env.host_manager.is_blacklisted(dead_host)

        attempts = []
        real = shuffle_mod.read_bucket

        def spy(uri, *a):
            attempts.append(uri)
            return real(uri, *a)

        monkeypatch.setattr(shuffle_mod, "read_bucket", spy)
        got = []
        f.fetch(sid, 0, got.extend)
        assert got == items
        assert attempts == [live.addr], attempts   # dead never tried
    finally:
        live.stop()


def test_rank_hosts_orders_by_health():
    from dpark_tpu.hostatus import TaskHostManager
    hm = TaskHostManager()
    for _ in range(3):
        hm.task_failed_on("bad")
    hm.task_succeed_on("ok")
    hm.task_failed_on("meh")
    hm.task_succeed_on("meh")
    ranked = hm.rank_hosts(["bad", "meh", "ok"])
    assert ranked == ["ok", "meh", "bad"]
    assert hm.offer_choice(["bad", "meh", "ok"]) == "ok"
    # blacklisted hosts remain usable as last resorts
    assert hm.rank_hosts(["bad"]) == ["bad"]


# ---------------------------------------------------------------------------
# peer-lease liveness (ISSUE 20): fake-clock lease registry semantics
# ---------------------------------------------------------------------------

@pytest.fixture()
def lease_100ms():
    from dpark_tpu import conf, dcn
    old = conf.PEER_LEASE_MS
    conf.PEER_LEASE_MS = 100.0
    dcn.reset_liveness()
    yield
    conf.PEER_LEASE_MS = old
    dcn.reset_liveness()


def test_lease_lifecycle_fake_clock(lease_100ms):
    from dpark_tpu import dcn
    uri = "tcp://10.0.0.1:555"
    t0 = 1000.0
    dcn.note_peer_ok(uri, now=t0)
    assert dcn.peer_alive(uri, now=t0 + 0.05)
    # a failure INSIDE a live lease is an ordinary transient the retry
    # path owns — never suspicion
    dcn.note_peer_fail(uri, now=t0 + 0.05)
    assert dcn.peer_alive(uri, now=t0 + 0.06)
    assert dcn.liveness_stats()["lease_expiries"] == 0
    # a failure AFTER the lease lapsed marks suspect, counted ONCE per
    # transition no matter how many shard attempts pile on
    dcn.note_peer_fail(uri, now=t0 + 0.2)
    dcn.note_peer_fail(uri, now=t0 + 0.21)
    st = dcn.liveness_stats()
    assert st["lease_expiries"] == 1
    assert st["suspect"] == ["10.0.0.1:555"]
    assert not dcn.peer_alive(uri, now=t0 + 0.25)
    # re-probe: one lease interval later the peer gets a fresh chance
    assert dcn.peer_alive(uri, now=t0 + 0.35)
    # a success clears suspicion and renews the lease
    dcn.note_peer_fail(uri, now=t0 + 0.4)
    dcn.note_peer_ok(uri, now=t0 + 0.45)
    assert dcn.peer_alive(uri, now=t0 + 0.46)
    assert dcn.liveness_stats()["suspect"] == []


def test_lease_disabled_is_inert():
    from dpark_tpu import conf, dcn
    old = conf.PEER_LEASE_MS
    conf.PEER_LEASE_MS = 0
    try:
        dcn.reset_liveness()
        dcn.note_peer_fail("tcp://10.0.0.9:1")
        assert dcn.peer_alive("tcp://10.0.0.9:1")
        assert dcn.liveness_stats() is None
    finally:
        conf.PEER_LEASE_MS = old
        dcn.reset_liveness()


def test_server_error_renews_lease_never_suspects(tmp_path):
    """An application-level refusal proves the peer is ALIVE: fetch
    renews its lease instead of reporting a transport failure."""
    import os as _os
    from dpark_tpu import conf, dcn
    wd = str(tmp_path / "wd")
    _os.makedirs(wd)
    srv = dcn.BucketServer(wd, host="127.0.0.1").start()
    old = conf.PEER_LEASE_MS
    conf.PEER_LEASE_MS = 5000.0
    dcn.reset_liveness()
    try:
        uri = "tcp://%s:%d" % srv.bind_address
        with pytest.raises(dcn.ServerError):
            dcn.fetch(uri, ("no-such-kind",))
        st = dcn.liveness_stats()
        assert st["renewals"] >= 1
        assert st["suspect"] == []
        assert dcn.peer_alive(uri)
    finally:
        conf.PEER_LEASE_MS = old
        dcn.reset_liveness()
        srv.stop()


def test_conf_timeout_and_retry_knobs(monkeypatch):
    """ISSUE 20 satellite: the dcn fetch deadline and retry budget are
    conf-driven (DPARK_DCN_TIMEOUT_MS / DPARK_DCN_RETRIES), no longer
    hardcoded."""
    from dpark_tpu import conf, dcn
    monkeypatch.setattr(conf, "DCN_TIMEOUT_MS", 1234.0)
    assert dcn._timeout_s(None) == pytest.approx(1.234)
    assert dcn._timeout_s(7) == 7
    # an unreachable peer exhausts exactly DCN_RETRIES attempts
    monkeypatch.setattr(conf, "DCN_RETRIES", 2)
    monkeypatch.setattr(conf, "DCN_CONNECT_ATTEMPTS", 1)
    monkeypatch.setattr(conf, "DCN_CONNECT_BACKOFF", 0.001)
    with pytest.raises(OSError):
        dcn.fetch("tcp://127.0.0.1:1", ("ping",), timeout=0.2)
