"""Persistent AOT executable cache (ISSUE 17): the disk tier under
the executor's bounded program cache.

The suite proves the contracts the instant-on design makes:

* HYGIENE — corrupt, truncated, or version-drifted entries NEVER
  error and never feed a stale executable: every defect is a silent
  miss and the caller compiles (the adapt-store contract).
* SHARING — two concurrent writer processes on one cache directory
  interleave safely: whole entries or no entry (tmp+rename), torn
  index lines skip, contested keys resolve latest-wins.
* WRITE-BACK — eviction under DPARK_PROGRAM_CACHE_MAX persists a
  resolved-but-unstored executable before the memory tier drops it.
* PARITY — off/read/on produce bit-identical results on a chaos
  (injected fetch-fault) job; the modes differ only in counters.
* WARMING — boot warming ranks the index by the adapt store's
  observed compile-cost profiles and preloads under a deadline; the
  first proxy resolution consumes the preload instead of the disk.

Device tests run on a 2-device sliced mesh ("tpu:2") so the suite
works on small containers.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dpark_tpu import Columns, adapt, aotcache, conf, service
from dpark_tpu.utils import frame_jsonl, unframe_jsonl


@pytest.fixture(autouse=True)
def _fresh_planes(tmp_path):
    """Every test gets its own adapt store, no installed AOT plane to
    start, and restored conf knobs; no process-global server leaks."""
    old_budget = conf.AOT_WARM_BUDGET_MS
    adapt.configure(mode="observe", store_dir=str(tmp_path / "adapt"))
    aotcache.configure(mode="off")
    yield
    conf.AOT_WARM_BUDGET_MS = old_budget
    aotcache.configure(mode="off")
    adapt.configure()
    service.shutdown()


def _plane(tmp_path, mode="on"):
    return aotcache.configure(mode=mode,
                              cache_dir=str(tmp_path / "cache"))


def _mul(m):
    return jax.jit(lambda x, _m=m: x * _m)


def _compiled(m):
    x = jnp.arange(8, dtype=jnp.int32)
    return _mul(m).lower(x).compile(), x


def _add(a, b):
    return a + b


# ---------------------------------------------------------------------------
# modes and the off-mode seam
# ---------------------------------------------------------------------------

def test_mode_grammar(tmp_path):
    assert aotcache.configure(mode="off") is None
    assert not aotcache.active() and aotcache.plane() is None
    p = _plane(tmp_path, "read")
    assert p.mode == "read" and aotcache.active()
    with pytest.raises(ValueError):
        aotcache.configure(mode="sometimes")


def test_off_seams_are_inert():
    aotcache.configure(mode="off")
    assert aotcache.stats() is None
    # the sig stamp must be a no-op, not a crash, with no plane
    assert aotcache.set_current_sig(("p", "s")) is None


# ---------------------------------------------------------------------------
# store/load round trip and defect hygiene
# ---------------------------------------------------------------------------

def test_store_load_round_trip(tmp_path):
    plane = _plane(tmp_path)
    exe, x = _compiled(3)
    dk = plane.disk_key(("narrow", "k1"))
    assert plane.store(dk, exe, sig="p1|s0", compile_ms=12.5)
    got = plane.load(dk)
    assert got is not None
    np.testing.assert_array_equal(np.asarray(got(x)),
                                  np.asarray(x) * 3)
    st = plane.stats()
    assert st["stores"] == 1 and st["loads"] == 1
    assert st["load_misses"] == 0 and st["load_errors"] == 0
    idx = plane.index()
    assert idx[dk]["sig"] == "p1|s0" and idx[dk]["nbytes"] > 0


def test_missing_entry_is_a_miss_not_an_error(tmp_path):
    plane = _plane(tmp_path)
    assert plane.load(plane.disk_key(("narrow", "ghost"))) is None
    st = plane.stats()
    assert st["load_misses"] == 1 and st["load_errors"] == 0


@pytest.mark.parametrize("defect", ["flip", "truncate", "garbage"])
def test_corrupt_entries_fall_back_silently(tmp_path, defect):
    plane = _plane(tmp_path)
    exe, _ = _compiled(5)
    dk = plane.disk_key(("narrow", "kc"))
    assert plane.store(dk, exe)
    path = plane._entry_path(dk)
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    if defect == "flip":
        raw[-1] ^= 0xFF                       # payload bit rot
    elif defect == "truncate":
        raw = raw[:len(raw) // 2]             # torn write
    else:
        raw = bytearray(b"not an entry at all\n")
    with open(path, "wb") as f:
        f.write(bytes(raw))
    assert plane.load(dk) is None
    st = plane.stats()
    assert st["load_errors"] == 1 and st["load_misses"] == 1


def test_version_drift_skips_entry(tmp_path):
    plane = _plane(tmp_path)
    exe, _ = _compiled(4)
    dk = plane.disk_key(("narrow", "kv"))
    assert plane.store(dk, exe)
    path = plane._entry_path(dk)
    with open(path, "rb") as f:
        raw = f.read()
    head, _, rest = raw.partition(b"\n")
    recs, skipped = unframe_jsonl(head + b"\n")
    assert recs and not skipped
    header = recs[0]
    header["jax"] = "0.0.0-somebody-elses-build"
    # re-frame so the line crc still passes: the SKIP must come from
    # the version check, not from corruption hygiene
    with open(path, "wb") as f:
        f.write(frame_jsonl(header) + rest)
    assert plane.load(dk) is None
    st = plane.stats()
    assert st["version_skips"] == 1 and st["load_errors"] == 0


def test_proxy_recompiles_through_corruption(tmp_path):
    """End to end: a corrupt entry means the proxy compiles fresh and
    re-stores a good one — correctness never depends on the disk."""
    plane = _plane(tmp_path)
    x = jnp.arange(6, dtype=jnp.int32)
    prog = plane.wrap(("narrow", "kp"), _mul(7))
    np.testing.assert_array_equal(np.asarray(prog(x)),
                                  np.asarray(x) * 7)
    assert plane.stats()["stores"] == 1
    dk = plane.disk_key(("narrow", "kp"))
    with open(plane._entry_path(dk), "wb") as f:
        f.write(b"rotten\n")
    # a fresh plane (fresh process stand-in) sharing the dir
    plane2 = aotcache.configure(mode="on", cache_dir=plane.dir)
    prog2 = plane2.wrap(("narrow", "kp"), _mul(7))
    np.testing.assert_array_equal(np.asarray(prog2(x)),
                                  np.asarray(x) * 7)
    st = plane2.stats()
    assert st["load_errors"] == 1 and st["stores"] == 1
    # and the re-store healed the entry for the NEXT process
    plane3 = aotcache.configure(mode="read", cache_dir=plane.dir)
    assert plane3.load(dk) is not None


def test_read_mode_loads_but_never_writes(tmp_path):
    writer = _plane(tmp_path, "on")
    exe, x = _compiled(6)
    dk = writer.disk_key(("narrow", "kr"))
    assert writer.store(dk, exe)
    before = sorted(os.listdir(writer.dir))
    reader = aotcache.configure(mode="read", cache_dir=writer.dir)
    got = reader.load(dk)
    np.testing.assert_array_equal(np.asarray(got(x)),
                                  np.asarray(x) * 6)
    assert not reader.store(reader.disk_key(("narrow", "new")), exe)
    # and the whole-job path: a read-mode proxy with no entry falls
    # through to the live jit without writing anything
    prog = reader.wrap(("narrow", "absent"), _mul(2))
    np.testing.assert_array_equal(np.asarray(prog(x)),
                                  np.asarray(x) * 2)
    assert sorted(os.listdir(writer.dir)) == before
    st = reader.stats()
    assert st["loads"] == 1 and st["stores"] == 0


# ---------------------------------------------------------------------------
# eviction write-back under DPARK_PROGRAM_CACHE_MAX
# ---------------------------------------------------------------------------

def test_eviction_writes_back_before_dropping(tmp_path, monkeypatch):
    """A resolved executable whose initial store failed transiently
    (serialize hiccup) must persist on eviction, so a later re-insert
    loads instead of compiling."""
    from jax.experimental import serialize_executable as se
    from dpark_tpu.backend.tpu.executor import _ProgramCache
    plane = _plane(tmp_path)
    real = se.serialize
    calls = []

    def flaky(compiled):
        calls.append(1)
        if len(calls) == 1:
            raise ValueError("injected: serialize unavailable")
        return real(compiled)

    monkeypatch.setattr(se, "serialize", flaky)
    x = jnp.arange(4, dtype=jnp.int32)
    pc = _ProgramCache(cap=1)
    pc[("narrow", "a")] = _mul(2)
    np.testing.assert_array_equal(np.asarray(pc[("narrow", "a")](x)),
                                  np.asarray(x) * 2)
    st = plane.stats()
    assert st["store_errors"] == 1 and st["stores"] == 0
    pc[("narrow", "b")] = _mul(3)          # cap=1: evicts "a"
    st = plane.stats()
    assert st["stores"] == 1 and st["evict_writebacks"] == 1
    # the written-back entry round-trips in a fresh plane
    plane2 = aotcache.configure(mode="read", cache_dir=plane.dir)
    got = plane2.load(plane2.disk_key(("narrow", "a")))
    np.testing.assert_array_equal(np.asarray(got(x)),
                                  np.asarray(x) * 2)


def test_already_stored_proxy_does_not_write_back(tmp_path):
    from dpark_tpu.backend.tpu.executor import _ProgramCache
    plane = _plane(tmp_path)
    x = jnp.arange(4, dtype=jnp.int32)
    pc = _ProgramCache(cap=1)
    pc[("narrow", "a")] = _mul(2)
    pc[("narrow", "a")](x)                 # resolves AND stores
    pc[("narrow", "b")] = _mul(3)          # evicts "a"
    st = plane.stats()
    assert st["stores"] == 1 and st["evict_writebacks"] == 0


# ---------------------------------------------------------------------------
# concurrent writer processes sharing one cache directory
# ---------------------------------------------------------------------------

_WRITER = r"""
import sys
import jax

# match the spawning test process's x64 flag (executor construction
# flips it suite-wide): the version key covers x64, so a mismatched
# child would write entries the parent rightly refuses to load
jax.config.update("jax_enable_x64", bool(int(sys.argv[3])))
import jax.numpy as jnp
from dpark_tpu import aotcache

plane = aotcache.configure(mode="on", cache_dir=sys.argv[1])
mul = int(sys.argv[2])
x = jnp.arange(8, dtype=jnp.int32)
for i in range(2):
    fn = jax.jit(lambda v, _m=mul + i: v * _m)
    exe = fn.lower(x).compile()
    dk = plane.disk_key(("narrow", "own-%d-%d" % (mul, i)))
    assert plane.store(dk, exe, sig="p%d-%d|s0" % (mul, i),
                       compile_ms=1.0), (mul, i)
fn = jax.jit(lambda v, _m=mul * 100: v * _m)
exe = fn.lower(x).compile()
assert plane.store(plane.disk_key(("narrow", "contested")), exe,
                   sig="contested|s0", compile_ms=1.0)
st = plane.stats()
assert st["stores"] == 3 and st["store_errors"] == 0, st
print("WRITER_OK")
"""


def test_two_process_writers_share_one_dir(tmp_path):
    cache = str(tmp_path / "cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    x64 = str(int(bool(jax.config.jax_enable_x64)))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WRITER, cache, str(m), x64],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for m in (2, 9)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0 and "WRITER_OK" in out, out[-1500:]
    plane = aotcache.configure(mode="read", cache_dir=cache)
    idx = plane.index()
    # 2 own keys per writer + the contested key, all whole
    assert len(idx) == 5, sorted(idx)
    x = jnp.arange(8, dtype=jnp.int32)
    muls = []
    for dk in idx:
        exe = plane.load(dk)
        assert exe is not None, dk
        out = np.asarray(exe(x))
        assert out[0] == 0 and out[1] % 1 == 0
        muls.append(int(out[1]))
    # the contested entry is EXACTLY one writer's whole executable
    # (tmp+rename: no interleaved torn file), latest index line wins
    contested = plane.disk_key(("narrow", "contested"))
    got = int(np.asarray(plane.load(contested)(x))[1])
    assert got in (200, 900), got
    assert sorted(muls) == sorted([2, 3, 9, 10, got])
    st = plane.stats()
    assert st["load_errors"] == 0 and st["version_skips"] == 0


# ---------------------------------------------------------------------------
# boot warming: ledger-ranked, deadline-bounded, preload-consumed
# ---------------------------------------------------------------------------

def _seed_entries(plane, sigs, stored_ms=1.0):
    dks = {}
    for j, sig in enumerate(sigs):
        exe, _ = _compiled(j + 2)
        dk = plane.disk_key(("narrow", "seed", sig))
        assert plane.store(dk, exe, sig=sig, compile_ms=stored_ms)
        dks[sig] = dk
    return dks


def test_ranked_entries_order_by_observed_cost(tmp_path):
    plane = _plane(tmp_path)
    _seed_entries(plane, ["A|s", "B|s", "C|s"])
    # observed cost = compile ms x hits from the adapt store
    adapt.record_program_cost("A|s", {"hits": 10,
                                      "compile_ms": 100.0})
    adapt.record_program_cost("B|s", {"hits": 1,
                                      "compile_ms": 500.0})
    order = [r["sig"] for r in plane.ranked_entries()]
    assert order == ["A|s", "B|s", "C|s"]


def test_ranked_entries_tie_break_on_stored_compile_ms(tmp_path):
    plane = _plane(tmp_path)
    exe, _ = _compiled(2)
    fast = plane.disk_key(("narrow", "fast"))
    slow = plane.disk_key(("narrow", "slow"))
    assert plane.store(fast, exe, sig="F|s", compile_ms=2.0)
    assert plane.store(slow, exe, sig="S|s", compile_ms=9.0)
    # neither profiled: the storing process's measured compile ms
    # breaks the tie, hottest first
    order = [r["sig"] for r in plane.ranked_entries(costs={})]
    assert order == ["S|s", "F|s"]


def test_warm_respects_deadline_and_preloads(tmp_path):
    plane = _plane(tmp_path)
    _seed_entries(plane, ["W|s"])
    assert plane.warm(budget_ms=0)["warmed"] == 0   # spent budget
    summary = plane.warm(budget_ms=5000)
    assert summary["warmed"] == 1 and summary["entries"] == 1
    st = plane.stats()
    assert st["warmed"] == 1 and st["warm_pending"] == 1
    # the first proxy resolution consumes the preload — no disk read,
    # no compile
    x = jnp.arange(8, dtype=jnp.int32)
    prog = plane.wrap(("narrow", "seed", "W|s"), _mul(2))
    np.testing.assert_array_equal(np.asarray(prog(x)),
                                  np.asarray(x) * 2)
    st = plane.stats()
    assert st["warm_hits"] == 1 and st["warm_pending"] == 0
    assert st["loads"] == 0 and st["stores"] == 1


def test_service_boot_warm_reports(tmp_path):
    """A starting JobServer warms the cache dir under the conf budget
    and reports the summary through service_stats (billed to the
    __boot__ pseudo-tenant in traces)."""
    from dpark_tpu import DparkContext
    plane = _plane(tmp_path)
    _seed_entries(plane, ["A|s", "B|s"])
    adapt.record_program_cost("A|s", {"hits": 5, "compile_ms": 50.0})
    conf.AOT_WARM_BUDGET_MS = 5000.0
    c = DparkContext("service:tpu:2")
    c.start()
    try:
        warm = c.scheduler.service_stats().get("aot_warm")
        assert warm and warm["warmed"] == 2 and warm["entries"] == 2
        assert plane.stats()["warmed"] == 2
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# off/read/on chaos-matrix parity
# ---------------------------------------------------------------------------

def test_off_read_on_chaos_matrix_parity(tmp_path):
    """The same fetch-fault chaos job under every mode — plus a second
    `on` pass whose fresh executor resolves off disk — must agree
    bit-for-bit; only the counters may differ."""
    from dpark_tpu import DparkContext, faults
    cache = str(tmp_path / "cache")
    n = 4000
    i = np.arange(n, dtype=np.int64)
    data = Columns(i % 97, i)
    results, stats = {}, {}
    for run, mode in (("off", "off"), ("read", "read"),
                      ("on", "on"), ("on-warmdisk", "on")):
        aotcache.configure(mode=mode, cache_dir=cache)
        faults.configure("shuffle.fetch:p=0.2,seed=7,times=4")
        c = DparkContext("tpu:2")
        c.start()
        try:
            results[run] = sorted(
                c.parallelize(data, 2).reduceByKey(_add, 2).collect())
        finally:
            c.stop()
            faults.configure(None)
        stats[run] = aotcache.stats()
    assert results["off"] == results["read"] == results["on"] \
        == results["on-warmdisk"]
    assert stats["off"] is None
    assert stats["read"]["stores"] == 0
    assert stats["on"]["stores"] > 0
    # the second on-run's fresh executor memory-misses everything and
    # must find it all on disk
    assert stats["on-warmdisk"]["loads"] > 0
    assert stats["on-warmdisk"]["load_errors"] == 0
