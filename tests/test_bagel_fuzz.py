"""Columnarized object-Bagel parity fuzzer: random NUMERIC object
programs (random graphs, degrees, halting/emission schedules, monoids,
initial messages, message-target modes) must produce identical results
on the tpu master's device-columnarized path and the local master's
object loop — and must actually ride the device (every generated
program is columnarizable by construction).

r5 depth (VERDICT r4 #9): halt-and-send programs, computed non-neighbor
targets, single-message emission, tuple vertex values, many-distinct-
degree graphs near the class budget, and fallback-boundary programs
asserted to fall back AND match."""

import random

import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


def _build_program(rng, n):
    """Random but trace-safe object compute: branches only on the
    superstep, the (static) out-degree, and `msg is not None`."""
    from dpark_tpu.bagel import Message, Vertex

    a = rng.choice([1, 2])
    b = rng.choice([0, 1, 2])
    c = rng.randint(-3, 3)
    fb = rng.randint(-2, 2)         # no-mail fallback constant
    halt_s = rng.randint(1, 3)
    emit_set = set(rng.sample(range(4), rng.randint(1, 4)))
    mc1 = rng.choice([1, 2])
    mc2 = rng.randint(-2, 2)
    tuple_vals = rng.random() < 0.3
    # message-target mode: the vertex's own edges, a COMPUTED
    # non-neighbor, or just the first out-edge (variable message count)
    tmode = rng.choice(["edges", "computed", "first"])
    # halt-and-send: emit exactly at the halting superstep
    halt_and_send = rng.random() < 0.3
    tk = rng.randint(1, 5)

    def compute(vert, msg, agg, s):
        if tuple_vals:
            base, acc = vert.value
            got = msg if msg is not None else fb
            newv = (base * a + got * b + c, acc + got)
            mval = newv[0] * mc1 + mc2
        else:
            got = msg if msg is not None else fb
            newv = vert.value * a + got * b + c
            mval = newv * mc1 + mc2
        active = s < halt_s
        v = Vertex(vert.id, newv, vert.outEdges, active)
        emit_now = (s == halt_s) if halt_and_send \
            else (active and s in emit_set)
        if emit_now:
            if tmode == "computed":
                return (v, [Message((vert.id * tk + s) % n, mval)])
            if tmode == "first" and vert.outEdges:
                return (v, [Message(vert.outEdges[0].target_id, mval)])
            if tmode == "edges" and vert.outEdges:
                return (v, [Message(e.target_id, mval)
                            for e in vert.outEdges])
        return (v, [])

    return compute


def _build_graph(rng, ctx, n, tuple_vals):
    import operator

    from dpark_tpu.bagel import BasicCombiner, Edge, Vertex
    rows = []
    # degree ladder reaching past the old degree-8 cap, with enough
    # distinct degrees to stress the class-sliced tracing
    ladder = [0, 1, 1, 2, 3, 4, 5, 6, 7, 8, 9, 12, 17, 33]
    for i in range(n):
        deg = rng.choice(ladder)
        targets = [rng.randrange(n) for _ in range(deg)]
        val = (rng.randint(-5, 5), rng.randint(-2, 2)) if tuple_vals \
            else rng.randint(-5, 5)
        rows.append((i, Vertex(i, val, [Edge(t) for t in targets])))
    verts = ctx.parallelize(rows, rng.choice([2, 4]))
    init = [(rng.randrange(n), rng.randint(-4, 4))
            for _ in range(rng.randint(0, n // 2))]
    msgs = ctx.parallelize(init, 2)
    op = rng.choice([operator.add, min, max])
    return verts, msgs, BasicCombiner(op)


def _run_parity(seed, expect_device=True, n_override=None,
                graph_fn=None):
    from dpark_tpu import DparkContext
    from dpark_tpu.bagel import Bagel
    outs = []
    used = False
    for master in ("tpu", "local"):
        rng = random.Random(seed)        # same program on both masters
        c = DparkContext(master)
        c.start()
        try:
            n = n_override or rng.randint(6, 24)
            # the program draws from its OWN rng stream; the graph
            # builder needs only its tuple_vals outcome, re-derived
            # deterministically by _program_uses_tuples
            compute = _build_program(random.Random(seed * 7 + 1), n)
            build = graph_fn or _build_graph
            verts, msgs, combiner = build(
                rng, c, n, _program_uses_tuples(seed))
            final = Bagel.run(c, verts, msgs, compute,
                              combiner=combiner, max_superstep=6)
            outs.append(sorted(
                (vid, v.value, v.active)
                for vid, v in final.collect()))
            if master == "tpu":
                used = getattr(c.scheduler, "_pregel_device_used",
                               False)
        finally:
            c.stop()
    if expect_device:
        assert used, "seed %d did not ride the device" % seed
    else:
        assert not used, "seed %d must fall back" % seed
    assert outs[0] == outs[1], (seed, outs[0], outs[1])


def _program_uses_tuples(seed):
    """Re-derive _build_program's tuple_vals draw (9th random value of
    its rng stream) so the graph builder matches the program."""
    rng = random.Random(seed * 7 + 1)
    rng.choice([1, 2])
    rng.choice([0, 1, 2])
    rng.randint(-3, 3)
    rng.randint(-2, 2)
    rng.randint(1, 3)
    rng.sample(range(4), rng.randint(1, 4))
    rng.choice([1, 2])
    rng.randint(-2, 2)
    return rng.random() < 0.3


@pytest.mark.parametrize("seed", range(8))
def test_object_bagel_fuzz_parity(seed):
    _run_parity(seed)


def test_fallback_boundary_class_count():
    """More distinct degrees than the exact-class trace budget: with
    power-of-two DEGREE BUCKETS (the ISSUE 4 lift) the program now
    COLUMNARIZES — the class count collapses to <= 1 + log2(max degree)
    — and still matches; with bucketing disabled the old fallback (and
    parity) still holds."""
    from dpark_tpu import bagel as bagel_mod

    def graph(rng, ctx, n, tuple_vals):
        import operator
        from dpark_tpu.bagel import BasicCombiner, Edge, Vertex
        k = bagel_mod.MAX_DEGREE_CLASSES + 1
        nn = max(n, k + 2)
        rows = []
        for i in range(nn):
            deg = i % k                  # k distinct degrees: over cap
            targets = [(i + j + 1) % nn for j in range(deg)]
            val = (i % 5, 0) if tuple_vals else i % 5
            rows.append((i, Vertex(i, val, [Edge(t) for t in targets])))
        verts = ctx.parallelize(rows, 4)
        msgs = ctx.parallelize([], 2)
        return verts, msgs, BasicCombiner(operator.add)

    _run_parity(3, expect_device=True,
                n_override=bagel_mod.MAX_DEGREE_CLASSES + 3,
                graph_fn=graph)
    from dpark_tpu.backend.tpu import bagel_obj
    stats = dict(bagel_obj.LAST_RUN_STATS)
    assert stats["bucketed"] and stats["classes"] <= 11, stats

    old = bagel_mod.DEGREE_BUCKETS
    bagel_mod.DEGREE_BUCKETS = False
    try:
        _run_parity(3, expect_device=False,
                    n_override=bagel_mod.MAX_DEGREE_CLASSES + 3,
                    graph_fn=graph)
    finally:
        bagel_mod.DEGREE_BUCKETS = old


def test_fallback_boundary_degree():
    """One past MAX_DEGREE falls back (and matches); AT the cap rides
    the device."""
    import operator
    from dpark_tpu import bagel as bagel_mod
    from dpark_tpu.bagel import (Bagel, BasicCombiner, Edge, Message,
                                 Vertex)
    from dpark_tpu import DparkContext

    old = bagel_mod.MAX_DEGREE
    bagel_mod.MAX_DEGREE = 12            # keep the test cheap
    try:
        def compute(vert, msg, agg, s):
            got = msg if msg is not None else 0
            v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 1)
            if s < 1 and vert.outEdges:
                return (v, [Message(e.target_id, 1)
                            for e in vert.outEdges])
            return (v, [])

        for deg, expect_device in ((12, True), (13, False)):
            outs = []
            used = False
            for master in ("tpu", "local"):
                c = DparkContext(master)
                c.start()
                try:
                    n = 20
                    rows = [(i, Vertex(i, 0,
                                       [Edge((i + j) % n)
                                        for j in range(deg)]))
                            for i in range(n)]
                    final = Bagel.run(
                        c, c.parallelize(rows, 4), c.parallelize([], 2),
                        compute, combiner=BasicCombiner(operator.add),
                        max_superstep=4)
                    outs.append(sorted((vid, v.value)
                                       for vid, v in final.collect()))
                    if master == "tpu":
                        used = getattr(c.scheduler,
                                       "_pregel_device_used", False)
                finally:
                    c.stop()
            assert used == expect_device, (deg, used)
            assert outs[0] == outs[1], (deg, outs)
    finally:
        bagel_mod.MAX_DEGREE = old
