"""Columnarized object-Bagel parity fuzzer: random NUMERIC object
programs (random graphs, degrees, halting/emission schedules, monoids,
initial messages) must produce identical results on the tpu master's
device-columnarized path and the local master's object loop — and must
actually ride the device (every generated program is columnarizable by
construction)."""

import random

import pytest


def _build_program(rng):
    """Random but trace-safe object compute: branches only on the
    superstep, the (static) out-degree, and `msg is not None`."""
    from dpark_tpu.bagel import Message, Vertex

    a = rng.choice([1, 2])
    b = rng.choice([0, 1, 2])
    c = rng.randint(-3, 3)
    fb = rng.randint(-2, 2)         # no-mail fallback constant
    halt_s = rng.randint(1, 3)
    emit_set = set(rng.sample(range(4), rng.randint(1, 4)))
    mc1 = rng.choice([1, 2])
    mc2 = rng.randint(-2, 2)

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else fb
        newv = vert.value * a + got * b + c
        active = s < halt_s
        v = Vertex(vert.id, newv, vert.outEdges, active)
        if active and s in emit_set and vert.outEdges:
            return (v, [Message(e.target_id, newv * mc1 + mc2)
                        for e in vert.outEdges])
        return (v, [])

    return compute


def _build_graph(rng, ctx):
    import operator

    from dpark_tpu.bagel import BasicCombiner, Edge, Vertex
    n = rng.randint(4, 20)
    rows = []
    for i in range(n):
        deg = rng.choice([0, 1, 1, 2, 3])
        targets = [rng.randrange(n) for _ in range(deg)]
        rows.append((i, Vertex(i, rng.randint(-5, 5),
                               [Edge(t) for t in targets])))
    verts = ctx.parallelize(rows, rng.choice([2, 4]))
    init = [(rng.randrange(n), rng.randint(-4, 4))
            for _ in range(rng.randint(0, n // 2))]
    msgs = ctx.parallelize(init, 2)
    op = rng.choice([operator.add, min, max])
    return verts, msgs, BasicCombiner(op)


@pytest.mark.parametrize("seed", range(6))
def test_object_bagel_fuzz_parity(seed):
    from dpark_tpu import DparkContext
    from dpark_tpu.bagel import Bagel
    outs = []
    used = False
    for master in ("tpu", "local"):
        rng = random.Random(seed)        # same program on both masters
        c = DparkContext(master)
        c.start()
        try:
            compute = _build_program(rng)
            verts, msgs, combiner = _build_graph(rng, c)
            final = Bagel.run(c, verts, msgs, compute,
                              combiner=combiner, max_superstep=6)
            outs.append(sorted(
                (vid, v.value, v.active)
                for vid, v in final.collect()))
            if master == "tpu":
                used = getattr(c.scheduler, "_pregel_device_used",
                               False)
        finally:
            c.stop()
    assert used, "seed %d did not ride the device" % seed
    assert outs[0] == outs[1], (seed, outs[0], outs[1])
