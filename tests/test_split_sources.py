"""Intra-file splitting for compressed and CSV sources (SURVEY.md 2.2):
one .gz file with several members splits across tasks, one .bz2 with
several streams likewise, and CSV splits land only on record boundaries
even when quoted fields contain newlines."""

import bz2
import csv
import gzip
import io

import numpy as np
import pytest


def _write_multi_member_gz(path, nmembers, lines_per):
    with open(path, "wb") as out:
        n = 0
        for m in range(nmembers):
            buf = io.BytesIO()
            with gzip.GzipFile(fileobj=buf, mode="wb") as g:
                for _ in range(lines_per):
                    g.write(b"line-%06d\n" % n)
                    n += 1
            out.write(buf.getvalue())
    return ["line-%06d" % i for i in range(n)]


def test_gzip_one_file_multi_split(ctx, tmp_path):
    p = str(tmp_path / "multi.gz")
    expect = _write_multi_member_gz(p, 4, 500)
    r = ctx.textFile(p)
    r.split_size = 1               # force one split per member
    splits = r.splits
    assert len(splits) == 4, [s.__dict__ for s in splits]
    got = r.collect()
    assert got == expect


def test_gzip_single_member_one_split(ctx, tmp_path):
    p = str(tmp_path / "one.gz")
    with gzip.open(p, "wt") as f:
        for i in range(100):
            f.write("x%d\n" % i)
    r = ctx.textFile(p)
    assert len(r.splits) == 1
    assert r.collect() == ["x%d" % i for i in range(100)]


def test_gzip_false_positive_magic_rejected(ctx, tmp_path):
    """Random bytes that happen to contain the gzip magic inside the
    compressed payload must not become split boundaries."""
    rng = np.random.RandomState(0)
    payload = rng.bytes(1 << 20) + b"\x1f\x8b\x08\x00" * 50
    lines = [payload.hex()[i:i + 64]
             for i in range(0, 4096, 64)]
    p = str(tmp_path / "fp.gz")
    with gzip.open(p, "wt") as f:
        for ln in lines:
            f.write(ln + "\n")
    # append a REAL second member so the scan has work to do
    with open(p, "ab") as out:
        buf = io.BytesIO()
        with gzip.GzipFile(fileobj=buf, mode="wb") as g:
            g.write(b"tail\n")
        out.write(buf.getvalue())
    r = ctx.textFile(p)
    r.split_size = 1
    assert r.collect() == lines + ["tail"]


def test_bzip2_multi_stream_split(ctx, tmp_path):
    p = str(tmp_path / "multi.bz2")
    expect = []
    with open(p, "wb") as out:
        for s in range(3):
            block = "".join("s%d-%d\n" % (s, i) for i in range(200))
            expect.extend(block.splitlines())
            out.write(bz2.compress(block.encode()))
    r = ctx.textFile(p)
    r.split_size = 1
    assert len(r.splits) == 3
    assert r.collect() == expect


def test_bzip2_single_stream_block_split(ctx, tmp_path):
    """ONE bz2 stream with several 100KB blocks (compresslevel=1) must
    split at the bit-aligned block magics — the round-2 gap was
    splitting only at byte-aligned stream starts (VERDICT r2 ask #9)."""
    p = str(tmp_path / "one_stream.bz2")
    lines = ["line-%06d %s" % (i, "x" * (i % 37)) for i in range(14000)]
    text = "\n".join(lines) + "\n"
    assert len(text) > 350000                   # > 3 blocks at level 1
    with open(p, "wb") as f:
        f.write(bz2.compress(text.encode(), compresslevel=1))
    r = ctx.textFile(p, splitSize=6000)   # compressed bytes
    from dpark_tpu.rdd import Bz2BlockSplit
    assert len(r.splits) >= 3, len(r.splits)
    assert all(isinstance(s, Bz2BlockSplit) for s in r.splits)
    assert r.collect() == lines
    # parallelism is real: distinct splits own distinct line ranges
    per_split = [len(list(r.compute(s))) for s in r.splits]
    assert sum(per_split) == len(lines)
    assert max(per_split) < len(lines)


def test_bzip2_block_split_line_spans_blocks(ctx, tmp_path):
    """A single line larger than a whole compression block: exactly one
    split owns it, none lose or duplicate it."""
    p = str(tmp_path / "giant.bz2")
    import random
    rng = random.Random(5)
    giant = "".join(rng.choice("abcdefgh ") for _ in range(250000))
    lines = ["head-%d" % i for i in range(2000)] + [giant] + \
            ["tail-%d" % i for i in range(2000)]
    with open(p, "wb") as f:
        f.write(bz2.compress(("\n".join(lines) + "\n").encode(),
                             compresslevel=1))
    r = ctx.textFile(p, splitSize=15000)
    assert len(r.splits) >= 2
    assert r.collect() == lines


def test_bzip2_multi_stream_block_split(ctx, tmp_path):
    """Concatenated streams each with multiple blocks; also exercises
    per-stream levels and the tpu master's host prologue over bz2."""
    p = str(tmp_path / "ms.bz2")
    expect = []
    with open(p, "wb") as out:
        for s, level in ((0, 1), (1, 2)):
            block = "".join("s%d-%06d\n" % (s, i) for i in range(25000))
            expect.extend(block.splitlines())
            out.write(bz2.compress(block.encode(), compresslevel=level))
    r = ctx.textFile(p, splitSize=5000)
    assert len(r.splits) >= 4
    assert r.collect() == expect


def test_csv_quoted_newline_across_split(ctx, tmp_path):
    """A quoted field containing newlines straddles the naive split
    boundary; the quote-parity scan must keep the record whole."""
    p = str(tmp_path / "q.csv")
    rows = []
    for i in range(500):
        if i % 50 == 7:
            rows.append([str(i), "multi\nline\nfield %d" % i, "z"])
        else:
            rows.append([str(i), "plain %d" % i, "z"])
    with open(p, "w", newline="") as f:
        csv.writer(f).writerows(rows)
    r = ctx.csvFile(p, splitSize=900)      # many tiny splits
    assert len(r.splits) > 5
    got = r.collect()
    assert got == rows


def test_csv_doubled_quotes(ctx, tmp_path):
    p = str(tmp_path / "dq.csv")
    rows = [[str(i), 'say ""hi""\nthere %d' % i] for i in range(300)]
    with open(p, "w", newline="") as f:
        csv.writer(f).writerows(rows)
    r = ctx.csvFile(p, splitSize=700)
    got = r.collect()
    expect = list(csv.reader(open(p, newline="")))
    assert got == expect


def test_csv_numsplits_and_quotechar(ctx, tmp_path):
    class SQ(csv.Dialect):
        delimiter = ","
        quotechar = "'"
        quoting = csv.QUOTE_MINIMAL
        lineterminator = "\r\n"
        doublequote = True
    csv.register_dialect("squote", SQ)
    p = str(tmp_path / "sq.csv")
    rows = [[str(i), "nl\nin field %d" % i] for i in range(200)]
    with open(p, "w", newline="") as f:
        csv.writer(f, "squote").writerows(rows)
    r = ctx.csvFile(p, dialect="squote", numSplits=6)
    assert len(r.splits) >= 4          # numSplits drives split size
    assert r.collect() == rows


def test_compressed_sources_over_chunkserver(ctx, tmp_path):
    """gzip/csv sources route ALL IO through file_manager, so they work
    on a DFS scheme path too."""
    from dpark_tpu.file_manager.chunkserver import ChunkServer
    root = tmp_path / "dfs"
    root.mkdir()
    expect = _write_multi_member_gz(str(root / "m.gz"), 3, 50)
    with open(root / "r.csv", "w", newline="") as f:
        csv.writer(f).writerows([["a", "x\ny"], ["b", "z"]])
    srv = ChunkServer(str(root)).start()
    try:
        r = ctx.textFile("cfs://%s/m.gz" % srv.addr)
        r.split_size = 1
        assert len(r.splits) == 3
        assert r.collect() == expect
        got = ctx.csvFile("cfs://%s/r.csv" % srv.addr).collect()
        assert got == [["a", "x\ny"], ["b", "z"]]
    finally:
        srv.stop()


def test_csv_bare_quote_in_unquoted_field(ctx, tmp_path):
    """A stray quote in an unquoted field (legal to csv.reader) must not
    poison later split boundaries — the exact state machine ignores it
    where a quote-parity count would flip forever."""
    p = str(tmp_path / "bare.csv")
    with open(p, "w", newline="") as f:
        f.write('1,5" nail,plain\r\n')        # bare quote, unquoted
        for i in range(300):
            f.write('%d,"multi\nline %d",z\r\n' % (i, i))
    expect = list(csv.reader(open(p, newline="")))
    r = ctx.csvFile(p, splitSize=500)
    assert len(r.splits) > 3
    assert r.collect() == expect


@pytest.mark.mesh
def test_csvfile_rides_device_text_path(tmp_path):
    """csvFile chains reach the device text-ingest path on the tpu
    master."""
    from dpark_tpu import DparkContext
    p = str(tmp_path / "dev.csv")
    with open(p, "w", newline="") as f:
        csv.writer(f).writerows(
            [["k%d" % (i % 7), str(i % 3)] for i in range(500)])
    tctx = DparkContext("tpu")
    tctx.start()
    try:
        got = dict(tctx.csvFile(p)
                   .map(lambda row: (row[0], int(row[1])))
                   .reduceByKey(lambda a, b: a + b, 4).collect())
        assert tctx.scheduler.executor.shuffle_store, "host fallback"
        lctx = DparkContext("local")
        expect = dict(lctx.csvFile(p)
                      .map(lambda row: (row[0], int(row[1])))
                      .reduceByKey(lambda a, b: a + b, 4).collect())
        lctx.stop()
        assert got == expect
    finally:
        tctx.stop()


def test_gzip_splitsize_via_textfile(ctx, tmp_path):
    p = str(tmp_path / "s.gz")
    expect = _write_multi_member_gz(p, 4, 100)
    r = ctx.textFile(p, splitSize=1)       # forwarded to member grouping
    assert len(r.splits) == 4
    assert r.collect() == expect


def test_csv_roundtrip_save_load(ctx, tmp_path):
    data = [["a", "1"], ["b", "2"], ["c,d", "3"]]
    ctx.parallelize(data, 2).saveAsCSVFile(str(tmp_path / "csv"))
    back = ctx.csvFile(str(tmp_path / "csv")).collect()
    assert sorted(back) == sorted(data)
