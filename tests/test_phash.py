"""portable_hash consistency: host Python vs device jnp (and later C++)."""

import numpy as np

from dpark_tpu.utils.phash import portable_hash, phash_device, phash_np


def test_basic_types_deterministic():
    assert portable_hash(None) == portable_hash(None)
    assert portable_hash(42) == portable_hash(42)
    assert portable_hash("abc") == portable_hash("abc")
    assert portable_hash(b"abc") == portable_hash("abc")
    assert portable_hash((1, "a")) == portable_hash((1, "a"))
    assert portable_hash(1.0) == portable_hash(1)
    assert portable_hash(True) == portable_hash(1)


def test_distribution():
    n = 64
    buckets = [0] * n
    for i in range(10000):
        buckets[portable_hash(i) % n] += 1
    assert max(buckets) < 2.0 * 10000 / n


def test_host_device_agree():
    keys = np.array([0, 1, 2, -1, -2, 123456, -123456, 2**31 - 1,
                     -(2**31)], dtype=np.int32)
    dev = np.asarray(phash_device(keys))
    host = np.array([portable_hash(int(k)) for k in keys], dtype=np.uint64)
    assert (dev.astype(np.uint64) == host).all()


def test_numpy_twin_bit_identical():
    """phash_np is load-bearing for device Bagel routing: vertices are
    partitioned with it while messages route via phash_device — any
    divergence silently drops every message."""
    import jax
    jax.config.update("jax_enable_x64", True)   # device twin needs i64
    rng = np.random.RandomState(0)
    for dt in (np.int32, np.int64):
        info = np.iinfo(dt)
        keys = np.concatenate([
            rng.randint(info.min, info.max, 500).astype(dt),
            np.array([0, 1, -1, info.min, info.max], dt)])
        h_np = phash_np(keys)
        h_dev = np.asarray(phash_device(keys)).astype(np.uint32)
        assert np.array_equal(h_np, h_dev), dt
        h_py = np.array([portable_hash(int(k)) for k in keys],
                        np.uint64)
        assert np.array_equal(h_np.astype(np.uint64), h_py), dt


def test_tuple_and_str_spread():
    hs = {portable_hash(("word", i)) for i in range(1000)}
    assert len(hs) == 1000
