"""portable_hash consistency: host Python vs device jnp (and later C++)."""

import numpy as np

from dpark_tpu.utils.phash import portable_hash, phash_device, phash_np


def test_basic_types_deterministic():
    assert portable_hash(None) == portable_hash(None)
    assert portable_hash(42) == portable_hash(42)
    assert portable_hash("abc") == portable_hash("abc")
    assert portable_hash(b"abc") == portable_hash("abc")
    assert portable_hash((1, "a")) == portable_hash((1, "a"))
    assert portable_hash(1.0) == portable_hash(1)
    assert portable_hash(True) == portable_hash(1)


def test_distribution():
    n = 64
    buckets = [0] * n
    for i in range(10000):
        buckets[portable_hash(i) % n] += 1
    assert max(buckets) < 2.0 * 10000 / n


def test_host_device_agree():
    keys = np.array([0, 1, 2, -1, -2, 123456, -123456, 2**31 - 1,
                     -(2**31)], dtype=np.int32)
    dev = np.asarray(phash_device(keys))
    host = np.array([portable_hash(int(k)) for k in keys], dtype=np.uint64)
    assert (dev.astype(np.uint64) == host).all()


def test_numpy_twin_bit_identical():
    """phash_np is load-bearing for device Bagel routing: vertices are
    partitioned with it while messages route via phash_device — any
    divergence silently drops every message."""
    import jax
    jax.config.update("jax_enable_x64", True)   # device twin needs i64
    rng = np.random.RandomState(0)
    for dt in (np.int32, np.int64):
        info = np.iinfo(dt)
        keys = np.concatenate([
            rng.randint(info.min, info.max, 500).astype(dt),
            np.array([0, 1, -1, info.min, info.max], dt)])
        h_np = phash_np(keys)
        h_dev = np.asarray(phash_device(keys)).astype(np.uint32)
        assert np.array_equal(h_np, h_dev), dt
        h_py = np.array([portable_hash(int(k)) for k in keys],
                        np.uint64)
        assert np.array_equal(h_np.astype(np.uint64), h_py), dt


def test_tuple_and_str_spread():
    hs = {portable_hash(("word", i)) for i in range(1000)}
    assert len(hs) == 1000


def _tuple_key_cols(rng, ncols, n=700):
    cols = [rng.randint(-2 ** 62, 2 ** 62, n).astype(np.int64)
            for _ in range(ncols)]
    # edge rows: zeros, +-1, int32/int64 extremes in every column
    edges = np.array([0, 1, -1, 2 ** 31 - 1, -(2 ** 31), 2 ** 62,
                      -(2 ** 62)], np.int64)
    return [np.concatenate([c, edges]) for c in cols]


def test_pair_hash_parity_py_np_cpp():
    """Composite (tuple) keys hash identically on the pure-Python host
    partitioner, the numpy twin, and the C++ bulk path — the routing
    contract that lets ((u, i), v) records ride the device shuffle and
    still land where HashPartitioner.get_partition expects."""
    from dpark_tpu.utils.phash import phash_np_cols
    from dpark_tpu.native import get_lib, phash_i64_cols_bulk
    rng = np.random.RandomState(11)
    for ncols in (2, 3, 4):
        cols = _tuple_key_cols(rng, ncols)
        py = np.array(
            [portable_hash(tuple(int(c[i]) for c in cols))
             for i in range(len(cols[0]))], np.uint32)
        assert np.array_equal(py, phash_np_cols(cols)), ncols
        cc = phash_i64_cols_bulk(cols)
        assert np.array_equal(py, cc), (ncols, get_lib() is not None)


def test_pair_hash_parity_device():
    """jnp twin of the composite hash: bit-identical to portable_hash
    over int64 AND int32 column dtypes (the ingest wire-narrowing can
    hand the device i32 columns)."""
    import jax
    jax.config.update("jax_enable_x64", True)
    from dpark_tpu.utils.phash import phash_device_cols
    rng = np.random.RandomState(12)
    for ncols in (2, 3):
        cols = _tuple_key_cols(rng, ncols)
        py = np.array(
            [portable_hash(tuple(int(c[i]) for c in cols))
             for i in range(len(cols[0]))], np.uint64)
        dev = np.asarray(phash_device_cols(cols)).astype(np.uint64)
        assert np.array_equal(py, dev), ncols
    # int32-dtype columns hash as their (sign-extended) values
    small = [rng.randint(-2 ** 31, 2 ** 31, 500).astype(np.int32)
             for _ in range(2)]
    py = np.array([portable_hash((int(small[0][i]), int(small[1][i])))
                   for i in range(500)], np.uint64)
    dev = np.asarray(phash_device_cols(small)).astype(np.uint64)
    assert np.array_equal(py, dev)


def test_single_column_cols_matches_scalar_hash():
    """phash_*_cols degenerate to the scalar hash for one column (the
    composite combine must NOT fire for scalar keys — partition layouts
    of existing jobs may not move)."""
    from dpark_tpu.utils.phash import phash_np_cols
    keys = np.array([0, 1, -1, 12345, -(2 ** 40)], np.int64)
    assert np.array_equal(phash_np_cols([keys]), phash_np(keys))
