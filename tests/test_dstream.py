"""DStream tests — queueStream-driven with manual batch stepping for
determinism (reference style: tests/test_dstream.py collects per-batch
outputs and asserts sequences, SURVEY.md section 4)."""

import operator
import time

import pytest

from dpark_tpu.dstream import StreamingContext


def make_ssc(ctx, batch=1.0):
    return StreamingContext(ctx, batch)


def run_batches(ssc, n, t0=1000.0):
    """Deterministic manual clock: run n batches without the timer."""
    ssc.ctx.start()
    for ins in ssc.input_streams:
        if type(ins).__name__ != "SocketInputDStream":
            ins.start()
    ssc.zero_time = t0
    for k in range(1, n + 1):
        ssc.run_batch(t0 + k * ssc.batch_duration)


def test_map_filter_stream(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[1, 2, 3], [4, 5, 6]])
    q.map(lambda x: x * 2).filter(lambda x: x > 4).collect_batches(out)
    run_batches(ssc, 2)
    assert [sorted(v) for _, v in out] == [[6], [8, 10, 12]]


def test_flatmap_glom_count(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([["a b", "c"], ["d e f"]])
    q.flatMap(lambda line: line.split()).countByValue().collect_batches(out)
    run_batches(ssc, 2)
    assert dict(out[0][1]) == {"a": 1, "b": 1, "c": 1}
    assert dict(out[1][1]) == {"d": 1, "e": 1, "f": 1}


def test_reduce_by_key_stream(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("a", 1), ("a", 2), ("b", 1)]])
    q.reduceByKey(operator.add).collect_batches(out)
    run_batches(ssc, 1)
    assert dict(out[0][1]) == {"a": 3, "b": 1}


def test_window(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[1], [2], [3], [4]])
    q.window(2.0).collect_batches(out)
    run_batches(ssc, 4)
    assert [sorted(v) for _, v in out] == [[1], [1, 2], [2, 3], [3, 4]]


def test_count_by_window(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[1, 1], [2], [3, 3, 3], []])
    q.countByWindow(2.0).collect_batches(out)
    run_batches(ssc, 4)
    assert [v for _, v in out] == [[2], [3], [4], [3]]


def test_reduce_by_key_and_window_plain(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[("k", 1)], [("k", 2)], [("k", 4)], [("k", 8)]])
    q.reduceByKeyAndWindow(operator.add, 2.0).collect_batches(out)
    run_batches(ssc, 4)
    assert [dict(v) for _, v in out] == [
        {"k": 1}, {"k": 3}, {"k": 6}, {"k": 12}]


def test_reduce_by_key_and_window_incremental(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[("k", 1)], [("k", 2)], [("k", 4)], [("k", 8)]])
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    run_batches(ssc, 4)
    assert [dict(v) for _, v in out] == [
        {"k": 1}, {"k": 3}, {"k": 6}, {"k": 12}]


def test_update_state_by_key(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("a", 1)], [("a", 2), ("b", 5)], [("b", 1)]])

    def update(new_values, prev):
        return sum(new_values) + (prev or 0)

    q.updateStateByKey(update).collect_batches(out)
    run_batches(ssc, 3)
    assert dict(out[0][1]) == {"a": 1}
    assert dict(out[1][1]) == {"a": 3, "b": 5}
    assert dict(out[2][1]) == {"a": 3, "b": 6}


def _device_kinds(c, last_only=False):
    """(rdd, kind) pairs across the scheduler history, skipping
    single-task jobs (probe/take jobs run object tasks by design).
    last_only restricts to the final multi-task job — the steady-state
    batch."""
    recs = [rec for rec in c.scheduler.history
            if rec.get("parts") != 1]
    if last_only:
        recs = recs[-1:]
    kinds = set()
    for rec in recs:
        for st in rec.get("stage_info", []):
            kinds.add((st["rdd"], st.get("kind")))
    return kinds


@pytest.mark.mesh
def test_stateful_wordcount_rides_device_end_to_end():
    """The running-sum updateStateByKey idiom rewrites to one flat
    union-reduce per batch (VERDICT r4 #5), so on the tpu master every
    steady-state stage rides the array path — asserted by stage kinds,
    with values matching the local master."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[("w%d" % (i % 9), 1) for i in range(j * 17,
                                                        j * 17 + 300)]
                   for j in range(5)]
        # int-keyed variant keeps the whole pipeline on device
        batches = [[(hash(k) % 64, v) for k, v in b] for b in batches]
        q = ssc.queueStream(batches)

        def update(vs, prev):
            return (prev or 0) + sum(vs)

        q.updateStateByKey(update, numSplits=8).collect_batches(out)
        run_batches(ssc, 5)
        kinds = _device_kinds(c)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    assert {k for k, v in kinds} >= {"UnionRDD", "ShuffledRDD"}, kinds
    assert {v for k, v in kinds} == {"array"}, kinds


def test_state_monoid_hint_and_fallback(ctx):
    """__dpark_state_monoid__ opts an equivalent-but-unprovable update
    into the rewrite; a non-numeric stream keeps the cogroup path with
    identical results."""
    from dpark_tpu.dstream import _classify_state_update
    import operator

    def total(vs, prev):
        acc = prev if prev is not None else 0
        for v in vs:
            acc += v
        return acc
    assert _classify_state_update(total) is None
    total.__dpark_state_monoid__ = "add"
    assert _classify_state_update(total) is operator.add

    # string values: sum() would raise on the host path; the probe
    # must keep such streams off the pairwise rewrite
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("k", "a")], [("k", "b")]])

    def concat(vs, prev):
        s = prev or ""
        for v in vs:
            s += v
        return s

    q.updateStateByKey(concat).collect_batches(out)
    run_batches(ssc, 2)
    assert dict(out[1][1]) == {"k": "ab"}


def test_state_eviction(ctx):
    """update returning None drops the key."""
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("a", 1), ("b", 1)], [("b", 1)], [("b", 1)]])

    def update(new_values, prev):
        if not new_values:
            return None                 # evict idle keys
        return sum(new_values) + (prev or 0)

    q.updateStateByKey(update).collect_batches(out)
    run_batches(ssc, 3)
    assert dict(out[2][1]) == {"b": 3}


def test_union_join_streams(ctx):
    ssc = make_ssc(ctx)
    out_u, out_j = [], []
    a = ssc.queueStream([[("x", 1)], [("y", 2)]])
    b = ssc.queueStream([[("x", 10)], [("y", 20)]])
    a.union(b).collect_batches(out_u)
    a.join(b).collect_batches(out_j)
    run_batches(ssc, 2)
    assert sorted(out_u[0][1]) == [("x", 1), ("x", 10)]
    assert out_j[0][1] == [("x", (1, 10))]
    assert out_j[1][1] == [("y", (2, 20))]


def test_transform_with_time(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[1], [2]])
    q.transform(lambda rdd, t: rdd.map(lambda x: (x, t))) \
     .collect_batches(out)
    run_batches(ssc, 2, t0=100.0)
    assert out[0][1] == [(1, 101.0)]
    assert out[1][1] == [(2, 102.0)]


def test_file_input_stream(ctx, tmp_path):
    d = tmp_path / "stream"
    d.mkdir()
    ssc = make_ssc(ctx)
    out = []
    s = ssc.textFileStream(str(d))
    s.collect_batches(out)
    ssc.ctx.start()
    s.start()
    ssc.zero_time = 0.0
    (d / "f1.txt").write_text("l1\nl2\n")
    ssc.run_batch(1.0)
    (d / "f2.txt").write_text("l3\n")
    ssc.run_batch(2.0)
    ssc.run_batch(3.0)
    assert [v for _, v in out] == [["l1", "l2"], ["l3"]]


def test_timer_driven_end_to_end(ctx):
    """Real timer path: small batches, wait for results."""
    ssc = make_ssc(ctx, batch=0.2)
    out = []
    q = ssc.queueStream([[("a", 1)], [("a", 2)], [("a", 4)]])
    q.reduceByKey(operator.add).collect_batches(out)
    ssc.start()
    deadline = time.time() + 10
    while len(out) < 3 and time.time() < deadline:
        time.sleep(0.05)
    ssc.stop()
    assert len(out) >= 3
    got = [dict(v) for _, v in out[:3]]
    assert got == [{"a": 1}, {"a": 2}, {"a": 4}]


def test_socket_text_stream(ctx):
    import socket
    import threading
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]

    def serve():
        conn, _ = server.accept()
        conn.sendall(b"hello\nworld\n")
        time.sleep(1.0)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    ssc = make_ssc(ctx, batch=0.2)
    out = []
    s = ssc.socketTextStream("127.0.0.1", port)
    s.collect_batches(out)
    ssc.start()
    deadline = time.time() + 8
    while not out and time.time() < deadline:
        time.sleep(0.05)
    ssc.stop()
    server.close()
    flat = [x for _, v in out for x in v]
    assert flat == ["hello", "world"]


def test_checkpoint_recovery(ctx, tmp_path):
    """Crash/restore: state stream resumes from the checkpointed batch
    (reference: StreamingContext recovery, SURVEY.md 5.4)."""
    import operator
    from dpark_tpu.dstream import StreamingContext
    ckdir = str(tmp_path / "stream_ck")

    out1 = []

    def create():
        ssc = StreamingContext(ctx, 1.0)
        ssc.checkpoint_interval = 2       # checkpoint every 2 batches
        q = ssc.queueStream([[("a", 1)], [("a", 2)], [("a", 4)]])
        q.updateStateByKey(
            lambda vs, prev: sum(vs) + (prev or 0)).collect_batches(out1)
        return ssc

    ssc = StreamingContext.getOrCreate(ckdir, create)
    assert ssc.checkpoint_path == ckdir
    ssc.ctx.start()
    ssc.zero_time = 1000.0
    for k in (1, 2):                       # two batches -> checkpoint at 2
        ssc.run_batch(1000.0 + k)
    assert dict(out1[-1][1]) == {"a": 3}
    assert ssc.last_checkpoint_t == 1002.0

    # "crash": recover a NEW context from disk
    ssc2 = StreamingContext.getOrCreate(ckdir, create)
    assert ssc2 is not ssc                 # restored, not re-created
    assert ssc2.last_checkpoint_t == 1002.0
    out2 = []
    # rewire the restored output to a fresh sink we can observe
    ssc2.output_streams[0].func = lambda rdd, t: out2.append(
        (t, rdd.collect()))
    ssc2.ctx.start()
    ssc2.run_batch(1003.0)                 # continues with queued batch 3
    assert dict(out2[-1][1]) == {"a": 7}   # 1+2 restored, +4


def test_recovery_timeline_rebase(ctx, tmp_path):
    """start() after recovery rebases the clock: no replay storm over the
    downtime gap, state carried as the new predecessor batch."""
    from dpark_tpu.dstream import StreamingContext
    ckdir = str(tmp_path / "rebase_ck")
    sink = []

    def create():
        ssc = StreamingContext(ctx, 1.0)
        ssc.checkpoint_interval = 1
        q = ssc.queueStream([[("k", 1)], [("k", 10)]])
        q.updateStateByKey(
            lambda vs, prev: sum(vs) + (prev or 0)).collect_batches(sink)
        return ssc

    ssc = StreamingContext.getOrCreate(ckdir, create)
    ssc.ctx.start()
    ssc.zero_time = 1000.0
    ssc.run_batch(1001.0)
    assert dict(sink[-1][1]) == {"k": 1}

    ssc2 = StreamingContext.getOrCreate(ckdir, create)
    assert getattr(ssc2, "_recovered", False)
    ssc2.ctx.start()
    ssc2._rebase_timeline(50000.0)       # hours later, new clock
    ssc2.output_streams[0].func = lambda rdd, t: sink.append(
        (t, rdd.collect()))
    ssc2.run_batch(50001.0)
    assert dict(sink[-1][1]) == {"k": 11}    # state carried across gap


@pytest.mark.mesh
def test_linear_window_rides_device_end_to_end():
    """(add, sub) reduceByKeyAndWindow rewrites the incremental update
    to prev + new - old as ONE flat union-reduce, so on the tpu master
    EVERY stage of the steady-state window rides the array path —
    asserted by stage kinds, with values matching the local master."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 7, i % 5) for i in range(j * 31, j * 31 + 200)]
                   for j in range(5)]
        q = ssc.queueStream(batches)
        q.reduceByKeyAndWindow(operator.add, 2.0,
                               invFunc=operator.sub).collect_batches(out)
        run_batches(ssc, 5)
        kinds = _device_kinds(c)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    assert {k for k, v in kinds} >= {"UnionRDD", "ShuffledRDD",
                                     "ParallelCollection"}, kinds
    assert {v for k, v in kinds} == {"array"}, kinds


def test_counter_window_keeps_join_semantics(ctx):
    """Counter supports + and - but is NOT a group (its - saturates at
    zero), so the (add, sub) linear rewrite must not apply — the value
    probe keeps such streams on the leftOuterJoin path (r4 review)."""
    from collections import Counter
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[("k", Counter(a=1))], [("k", Counter(a=2))],
                         [("k", Counter(a=4))], [("k", Counter(a=8))]])
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    run_batches(ssc, 4)
    assert [dict(v) for _, v in out] == [
        {"k": Counter(a=1)}, {"k": Counter(a=3)},
        {"k": Counter(a=6)}, {"k": Counter(a=12)}]


def _window_fuzz_run(master, seed):
    import random as _random
    from dpark_tpu import DparkContext
    rng = _random.Random(seed)
    nb = rng.randint(4, 7)
    window = float(rng.randint(1, 3))
    batches = []
    for _ in range(nb):
        if rng.random() < 0.25:
            batches.append([])               # empty micro-batch
        else:
            batches.append([(rng.randint(0, 12), rng.randint(-9, 9))
                            for _ in range(rng.randint(1, 120))])
    c = DparkContext(master)
    ssc = make_ssc(c, batch=1.0)
    out = []
    q = ssc.queueStream(batches)
    q.reduceByKeyAndWindow(operator.add, window,
                           invFunc=operator.sub).collect_batches(out)
    run_batches(ssc, nb)
    res = [(t, sorted(v)) for t, v in out]
    c.stop()
    return res


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.mesh
def test_window_fuzz_parity(seed):
    """Random incremental windows (sizes, empty batches) must match
    the local master exactly — the (add, sub) linear rewrite included."""
    assert _window_fuzz_run("tpu", seed) == _window_fuzz_run("local",
                                                             seed)


@pytest.mark.mesh
def test_noninv_window_rides_device():
    """reduceByKeyAndWindow WITHOUT invFunc recomputes each window as a
    union of batch RDDs feeding a reduce — the union-source device
    stage; every steady-state stage rides the array path."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 16, 1) for i in range(j * 13, j * 13 + 160)]
                   for j in range(4)]
        q = ssc.queueStream(batches)
        q.reduceByKeyAndWindow(operator.add, 2.0,
                               numSplits=8).collect_batches(out)
        run_batches(ssc, 4)
        kinds = _device_kinds(c)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    assert {v for k, v in kinds} == {"array"}, kinds


@pytest.mark.mesh
def test_stream_join_rides_device():
    """Per-batch stream joins expand on the device join source in
    steady state (both sides' shuffles HBM-resident)."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        left = [[(i % 32, i) for i in range(j * 11, j * 11 + 120)]
                for j in range(3)]
        right = [[(i % 32, i * 2) for i in range(j * 7, j * 7 + 90)]
                 for j in range(3)]
        a = ssc.queueStream(left)
        b = ssc.queueStream(right)
        a.join(b, numSplits=8) \
         .transform(lambda r: r.map(
             lambda kv: (kv[0], kv[1][0] + kv[1][1]))
             .reduceByKey(operator.add, 8)) \
         .collect_batches(out)
        run_batches(ssc, 3)
        kinds = _device_kinds(c, last_only=True)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    # steady state (the last batch's job) must be ALL device stages
    assert kinds and {v for _, v in kinds} == {"array"}, kinds


def test_state_rewrite_falls_back_on_type_error(ctx):
    """Satellite regression (r5 advisor, low): a stream whose FIRST
    batch is numeric locks the union-reduce rewrite in; a later batch
    with non-numeric values must NOT silently concatenate through the
    pairwise a+b — the checked op raises TypeError, run_batch disables
    the rewrite permanently and replays the batch through the generic
    updateFunc path (which faithfully reproduces sum()'s TypeError for
    strings, exactly like the reference)."""
    ssc = make_ssc(ctx)
    out = []
    batches = [
        [("a", 1), ("a", 2), ("b", 3)],       # numeric: probe locks in
        [("a", 1), ("a", "x"), ("b", 2)],     # poisoned tail
        [("a", 5), ("b", 1)],                  # numeric again
    ]
    q = ssc.queueStream(batches)

    def update(vs, prev):
        return (prev or 0) + sum(vs)

    state = q.updateStateByKey(update)
    state.collect_batches(out)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0

    ssc.run_batch(1001.0)
    assert dict(out[-1][1]) == {"a": 3, "b": 3}
    assert state._numeric is True             # rewrite engaged

    # poisoned batch: the rewrite falls back, and the generic path
    # reproduces the reference behavior (sum() raises for int+str)
    with pytest.raises(Exception) as ei:
        ssc.run_batch(1002.0)
    assert "TypeError" in str(ei.value) or isinstance(ei.value,
                                                      TypeError)
    assert state._numeric is False            # latched off for good

    # the stream recovers: the next numeric batch runs generically and
    # the accumulated state survived the dropped batch
    ssc.run_batch(1003.0)
    assert dict(out[-1][1]) == {"a": 8, "b": 4}


def test_window_rewrite_falls_back_on_type_error(ctx):
    """Same contract for the (add, sub) incremental window: a stream
    that defeats the 5-record probe must end up on the generic
    leftOuterJoin+invFunc path instead of silently diverging."""
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    batches = [[("k", 1)], [("k", 2)], [("k", "x")], [("k", 8)]]
    q = ssc.queueStream(batches)
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0
    ssc.run_batch(1001.0)
    ssc.run_batch(1002.0)
    assert dict(out[-1][1]) == {"k": 3}
    streams = [s for s in ssc._all_streams()
               if type(s).__name__ == "ReducedWindowedDStream"]
    assert streams and streams[0]._numeric is True
    # the poisoned batch disables the rewrite; whatever error surfaces
    # is the generic path's own (str in an (add, sub) window)
    try:
        ssc.run_batch(1003.0)
    except Exception:
        pass
    assert streams[0]._numeric is False


def test_rewrite_fallback_leaves_sibling_chains_intact(ctx):
    """Fallback surgery is scoped to the FAILING output chain: an
    independent healthy state stream must keep its batch-t state (the
    code-review repro: popping generated[t] globally made the healthy
    chain silently drop a batch and regress at t+1)."""
    ssc = make_ssc(ctx)
    out_a, out_b = [], []
    qa = ssc.queueStream([[("a", 1)], [("a", 10)], [("a", 100)]])
    qb = ssc.queueStream([[("b", 1)], [("b", "x")], [("b", 5)]])

    def update(vs, prev):
        return (prev or 0) + sum(vs)

    sa = qa.updateStateByKey(update)
    sb = qb.updateStateByKey(update)
    sa.collect_batches(out_a)
    sb.collect_batches(out_b)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0

    ssc.run_batch(1001.0)
    assert dict(out_a[-1][1]) == {"a": 1}
    # chain B poisons batch 2; chain A already emitted (or still must
    # emit) its batch-2 state and MUST NOT lose it
    try:
        ssc.run_batch(1002.0)
    except Exception:
        pass
    ssc.run_batch(1003.0)
    assert dict(out_a[-1][1]) == {"a": 111}   # 1 + 10 + 100, no gap
    assert sb._numeric is False               # only B latched off
    assert sa._numeric is not False


def test_checked_op_rejects_numpy_strings():
    """np.str_ carries dtype+shape; the checked op must not let it
    slip past as an 'array-like' and concatenate (code-review)."""
    import numpy as np
    import operator
    from dpark_tpu.dstream import _CheckedNumericOp, _NumericRewriteError
    op = _CheckedNumericOp(operator.add, "add")
    assert op(2, 3) == 5
    assert op(np.int64(2), 3) == 5
    with pytest.raises(_NumericRewriteError):
        op(np.str_("a"), np.str_("b"))
    with pytest.raises(_NumericRewriteError):
        op(1, "x")


def test_general_traceable_updatestate_rides_device():
    """A decayed-counter updateFunc — traceable but NOT a provable
    monoid fold — rewrites to flag-union + groupByKey + the state-mode
    SegMapOp: in steady state every stage rides the array path (state
    lives as HBM-resident columns, the per-batch cogroup and the
    vmapped update(prev, values) run on device), with values matching
    the local master.  The `prev is None` spelling is the traceable
    form (the dual trace sees the literal None); `prev or 0` forces a
    tracer bool and keeps the cogroup path."""
    from dpark_tpu import DparkContext

    def update(vs, prev):
        base = 0.0 if prev is None else prev
        return base * 0.9 + sum(vs)

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 11, (i * 3) % 7) for i in range(j * 13,
                                                         j * 13 + 250)]
                   for j in range(5)]
        q = ssc.queueStream(batches)
        q.updateStateByKey(update, numSplits=8).collect_batches(out)
        run_batches(ssc, 5)
        kinds = []
        for rec in c.scheduler.history:
            for st in rec.get("stage_info", ()):
                if st.get("kind") is not None:
                    kinds.append((st.get("rdd"), st["kind"]))
        c.stop()
        return ([sorted((int(k), round(float(v), 6)) for k, v in vals)
                 for _, vals in out], kinds)

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    # steady state: the union map stage AND the grouped-update reduce
    # stage are all-array
    steady = [v for _, v in kinds[-4:]]
    assert set(steady) == {"array"}, kinds


def test_untraceable_updatestate_keeps_cogroup_parity():
    """An updateFunc with data-dependent Python control flow cannot
    trace: the classification declines and the cogroup path answers —
    identical on both masters (including eviction via None)."""
    from dpark_tpu import DparkContext

    def update(vs, prev):
        total = (prev if prev is not None else 0) + sum(vs)
        if total > 40:                  # tracer-unsafe branch + evict
            return None
        return total

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 5, i % 4) for i in range(j * 7, j * 7 + 40)]
                   for j in range(4)]
        q = ssc.queueStream(batches)
        q.updateStateByKey(update, numSplits=4).collect_batches(out)
        run_batches(ssc, 4)
        c.stop()
        return [sorted((int(k), int(v)) for k, v in vals)
                for _, vals in out]

    assert drive("tpu") == drive("local")
