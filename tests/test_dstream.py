"""DStream tests — queueStream-driven with manual batch stepping for
determinism (reference style: tests/test_dstream.py collects per-batch
outputs and asserts sequences, SURVEY.md section 4)."""

import operator
import time

import pytest

from dpark_tpu.dstream import StreamingContext


def make_ssc(ctx, batch=1.0):
    return StreamingContext(ctx, batch)


def run_batches(ssc, n, t0=1000.0):
    """Deterministic manual clock: run n batches without the timer."""
    ssc.ctx.start()
    for ins in ssc.input_streams:
        if type(ins).__name__ != "SocketInputDStream":
            ins.start()
    ssc.zero_time = t0
    for k in range(1, n + 1):
        ssc.run_batch(t0 + k * ssc.batch_duration)


def test_map_filter_stream(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[1, 2, 3], [4, 5, 6]])
    q.map(lambda x: x * 2).filter(lambda x: x > 4).collect_batches(out)
    run_batches(ssc, 2)
    assert [sorted(v) for _, v in out] == [[6], [8, 10, 12]]


def test_flatmap_glom_count(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([["a b", "c"], ["d e f"]])
    q.flatMap(lambda line: line.split()).countByValue().collect_batches(out)
    run_batches(ssc, 2)
    assert dict(out[0][1]) == {"a": 1, "b": 1, "c": 1}
    assert dict(out[1][1]) == {"d": 1, "e": 1, "f": 1}


def test_reduce_by_key_stream(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("a", 1), ("a", 2), ("b", 1)]])
    q.reduceByKey(operator.add).collect_batches(out)
    run_batches(ssc, 1)
    assert dict(out[0][1]) == {"a": 3, "b": 1}


def test_window(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[1], [2], [3], [4]])
    q.window(2.0).collect_batches(out)
    run_batches(ssc, 4)
    assert [sorted(v) for _, v in out] == [[1], [1, 2], [2, 3], [3, 4]]


def test_count_by_window(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[1, 1], [2], [3, 3, 3], []])
    q.countByWindow(2.0).collect_batches(out)
    run_batches(ssc, 4)
    assert [v for _, v in out] == [[2], [3], [4], [3]]


def test_reduce_by_key_and_window_plain(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[("k", 1)], [("k", 2)], [("k", 4)], [("k", 8)]])
    q.reduceByKeyAndWindow(operator.add, 2.0).collect_batches(out)
    run_batches(ssc, 4)
    assert [dict(v) for _, v in out] == [
        {"k": 1}, {"k": 3}, {"k": 6}, {"k": 12}]


def test_reduce_by_key_and_window_incremental(ctx):
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[("k", 1)], [("k", 2)], [("k", 4)], [("k", 8)]])
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    run_batches(ssc, 4)
    assert [dict(v) for _, v in out] == [
        {"k": 1}, {"k": 3}, {"k": 6}, {"k": 12}]


def test_update_state_by_key(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("a", 1)], [("a", 2), ("b", 5)], [("b", 1)]])

    def update(new_values, prev):
        return sum(new_values) + (prev or 0)

    q.updateStateByKey(update).collect_batches(out)
    run_batches(ssc, 3)
    assert dict(out[0][1]) == {"a": 1}
    assert dict(out[1][1]) == {"a": 3, "b": 5}
    assert dict(out[2][1]) == {"a": 3, "b": 6}


def _device_kinds(c, last_only=False):
    """(rdd, kind) pairs across the scheduler history, skipping
    single-task jobs (probe/take jobs run object tasks by design).
    last_only restricts to the final multi-task job — the steady-state
    batch."""
    recs = [rec for rec in c.scheduler.history
            if rec.get("parts") != 1]
    if last_only:
        recs = recs[-1:]
    kinds = set()
    for rec in recs:
        for st in rec.get("stage_info", []):
            kinds.add((st["rdd"], st.get("kind")))
    return kinds


@pytest.mark.mesh
def test_stateful_wordcount_rides_device_end_to_end():
    """The running-sum updateStateByKey idiom rewrites to one flat
    union-reduce per batch (VERDICT r4 #5), so on the tpu master every
    steady-state stage rides the array path — asserted by stage kinds,
    with values matching the local master."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[("w%d" % (i % 9), 1) for i in range(j * 17,
                                                        j * 17 + 300)]
                   for j in range(5)]
        # int-keyed variant keeps the whole pipeline on device
        batches = [[(hash(k) % 64, v) for k, v in b] for b in batches]
        q = ssc.queueStream(batches)

        def update(vs, prev):
            return (prev or 0) + sum(vs)

        q.updateStateByKey(update, numSplits=8).collect_batches(out)
        run_batches(ssc, 5)
        kinds = _device_kinds(c)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    assert {k for k, v in kinds} >= {"UnionRDD", "ShuffledRDD"}, kinds
    assert {v for k, v in kinds} == {"array"}, kinds


def test_state_monoid_hint_and_fallback(ctx):
    """__dpark_state_monoid__ opts an equivalent-but-unprovable update
    into the rewrite; a non-numeric stream keeps the cogroup path with
    identical results."""
    from dpark_tpu.dstream import _classify_state_update
    import operator

    def total(vs, prev):
        acc = prev if prev is not None else 0
        for v in vs:
            acc += v
        return acc
    assert _classify_state_update(total) is None
    total.__dpark_state_monoid__ = "add"
    assert _classify_state_update(total) is operator.add

    # string values: sum() would raise on the host path; the probe
    # must keep such streams off the pairwise rewrite
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("k", "a")], [("k", "b")]])

    def concat(vs, prev):
        s = prev or ""
        for v in vs:
            s += v
        return s

    q.updateStateByKey(concat).collect_batches(out)
    run_batches(ssc, 2)
    assert dict(out[1][1]) == {"k": "ab"}


def test_state_eviction(ctx):
    """update returning None drops the key."""
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("a", 1), ("b", 1)], [("b", 1)], [("b", 1)]])

    def update(new_values, prev):
        if not new_values:
            return None                 # evict idle keys
        return sum(new_values) + (prev or 0)

    q.updateStateByKey(update).collect_batches(out)
    run_batches(ssc, 3)
    assert dict(out[2][1]) == {"b": 3}


def test_union_join_streams(ctx):
    ssc = make_ssc(ctx)
    out_u, out_j = [], []
    a = ssc.queueStream([[("x", 1)], [("y", 2)]])
    b = ssc.queueStream([[("x", 10)], [("y", 20)]])
    a.union(b).collect_batches(out_u)
    a.join(b).collect_batches(out_j)
    run_batches(ssc, 2)
    assert sorted(out_u[0][1]) == [("x", 1), ("x", 10)]
    assert out_j[0][1] == [("x", (1, 10))]
    assert out_j[1][1] == [("y", (2, 20))]


def test_transform_with_time(ctx):
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[1], [2]])
    q.transform(lambda rdd, t: rdd.map(lambda x: (x, t))) \
     .collect_batches(out)
    run_batches(ssc, 2, t0=100.0)
    assert out[0][1] == [(1, 101.0)]
    assert out[1][1] == [(2, 102.0)]


def test_file_input_stream(ctx, tmp_path):
    d = tmp_path / "stream"
    d.mkdir()
    ssc = make_ssc(ctx)
    out = []
    s = ssc.textFileStream(str(d))
    s.collect_batches(out)
    ssc.ctx.start()
    s.start()
    ssc.zero_time = 0.0
    (d / "f1.txt").write_text("l1\nl2\n")
    ssc.run_batch(1.0)
    (d / "f2.txt").write_text("l3\n")
    ssc.run_batch(2.0)
    ssc.run_batch(3.0)
    assert [v for _, v in out] == [["l1", "l2"], ["l3"]]


def test_timer_driven_end_to_end(ctx):
    """Real timer path: small batches, wait for results."""
    ssc = make_ssc(ctx, batch=0.2)
    out = []
    q = ssc.queueStream([[("a", 1)], [("a", 2)], [("a", 4)]])
    q.reduceByKey(operator.add).collect_batches(out)
    ssc.start()
    deadline = time.time() + 10
    while len(out) < 3 and time.time() < deadline:
        time.sleep(0.05)
    ssc.stop()
    assert len(out) >= 3
    got = [dict(v) for _, v in out[:3]]
    assert got == [{"a": 1}, {"a": 2}, {"a": 4}]


def test_socket_text_stream(ctx):
    import socket
    import threading
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]

    def serve():
        conn, _ = server.accept()
        conn.sendall(b"hello\nworld\n")
        time.sleep(1.0)
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    ssc = make_ssc(ctx, batch=0.2)
    out = []
    s = ssc.socketTextStream("127.0.0.1", port)
    s.collect_batches(out)
    ssc.start()
    deadline = time.time() + 8
    while not out and time.time() < deadline:
        time.sleep(0.05)
    ssc.stop()
    server.close()
    flat = [x for _, v in out for x in v]
    assert flat == ["hello", "world"]


def test_checkpoint_recovery(ctx, tmp_path):
    """Crash/restore: state stream resumes from the checkpointed batch
    (reference: StreamingContext recovery, SURVEY.md 5.4)."""
    import operator
    from dpark_tpu.dstream import StreamingContext
    ckdir = str(tmp_path / "stream_ck")

    out1 = []

    def create():
        ssc = StreamingContext(ctx, 1.0)
        ssc.checkpoint_interval = 2       # checkpoint every 2 batches
        q = ssc.queueStream([[("a", 1)], [("a", 2)], [("a", 4)]])
        q.updateStateByKey(
            lambda vs, prev: sum(vs) + (prev or 0)).collect_batches(out1)
        return ssc

    ssc = StreamingContext.getOrCreate(ckdir, create)
    assert ssc.checkpoint_path == ckdir
    ssc.ctx.start()
    ssc.zero_time = 1000.0
    for k in (1, 2):                       # two batches -> checkpoint at 2
        ssc.run_batch(1000.0 + k)
    assert dict(out1[-1][1]) == {"a": 3}
    assert ssc.last_checkpoint_t == 1002.0

    # "crash": recover a NEW context from disk
    ssc2 = StreamingContext.getOrCreate(ckdir, create)
    assert ssc2 is not ssc                 # restored, not re-created
    assert ssc2.last_checkpoint_t == 1002.0
    out2 = []
    # rewire the restored output to a fresh sink we can observe
    ssc2.output_streams[0].func = lambda rdd, t: out2.append(
        (t, rdd.collect()))
    ssc2.ctx.start()
    ssc2.run_batch(1003.0)                 # continues with queued batch 3
    assert dict(out2[-1][1]) == {"a": 7}   # 1+2 restored, +4


def test_recovery_timeline_rebase(ctx, tmp_path):
    """start() after recovery rebases the clock: no replay storm over the
    downtime gap, state carried as the new predecessor batch."""
    from dpark_tpu.dstream import StreamingContext
    ckdir = str(tmp_path / "rebase_ck")
    sink = []

    def create():
        ssc = StreamingContext(ctx, 1.0)
        ssc.checkpoint_interval = 1
        q = ssc.queueStream([[("k", 1)], [("k", 10)]])
        q.updateStateByKey(
            lambda vs, prev: sum(vs) + (prev or 0)).collect_batches(sink)
        return ssc

    ssc = StreamingContext.getOrCreate(ckdir, create)
    ssc.ctx.start()
    ssc.zero_time = 1000.0
    ssc.run_batch(1001.0)
    assert dict(sink[-1][1]) == {"k": 1}

    ssc2 = StreamingContext.getOrCreate(ckdir, create)
    assert getattr(ssc2, "_recovered", False)
    ssc2.ctx.start()
    ssc2._rebase_timeline(50000.0)       # hours later, new clock
    ssc2.output_streams[0].func = lambda rdd, t: sink.append(
        (t, rdd.collect()))
    ssc2.run_batch(50001.0)
    assert dict(sink[-1][1]) == {"k": 11}    # state carried across gap


@pytest.mark.mesh
def test_linear_window_rides_device_end_to_end():
    """(add, sub) reduceByKeyAndWindow rewrites the incremental update
    to prev + new - old as ONE flat union-reduce, so on the tpu master
    EVERY stage of the steady-state window rides the array path —
    asserted by stage kinds, with values matching the local master."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 7, i % 5) for i in range(j * 31, j * 31 + 200)]
                   for j in range(5)]
        q = ssc.queueStream(batches)
        q.reduceByKeyAndWindow(operator.add, 2.0,
                               invFunc=operator.sub).collect_batches(out)
        run_batches(ssc, 5)
        kinds = _device_kinds(c)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    assert {k for k, v in kinds} >= {"UnionRDD", "ShuffledRDD",
                                     "ParallelCollection"}, kinds
    assert {v for k, v in kinds} == {"array"}, kinds


def test_counter_window_keeps_join_semantics(ctx):
    """Counter supports + and - but is NOT a group (its - saturates at
    zero), so the (add, sub) linear rewrite must not apply — the value
    probe keeps such streams on the leftOuterJoin path (r4 review)."""
    from collections import Counter
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    q = ssc.queueStream([[("k", Counter(a=1))], [("k", Counter(a=2))],
                         [("k", Counter(a=4))], [("k", Counter(a=8))]])
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    run_batches(ssc, 4)
    assert [dict(v) for _, v in out] == [
        {"k": Counter(a=1)}, {"k": Counter(a=3)},
        {"k": Counter(a=6)}, {"k": Counter(a=12)}]


def _window_fuzz_run(master, seed):
    import random as _random
    from dpark_tpu import DparkContext
    rng = _random.Random(seed)
    nb = rng.randint(4, 7)
    window = float(rng.randint(1, 3))
    batches = []
    for _ in range(nb):
        if rng.random() < 0.25:
            batches.append([])               # empty micro-batch
        else:
            batches.append([(rng.randint(0, 12), rng.randint(-9, 9))
                            for _ in range(rng.randint(1, 120))])
    c = DparkContext(master)
    ssc = make_ssc(c, batch=1.0)
    out = []
    q = ssc.queueStream(batches)
    q.reduceByKeyAndWindow(operator.add, window,
                           invFunc=operator.sub).collect_batches(out)
    run_batches(ssc, nb)
    res = [(t, sorted(v)) for t, v in out]
    c.stop()
    return res


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.mesh
def test_window_fuzz_parity(seed):
    """Random incremental windows (sizes, empty batches) must match
    the local master exactly — the (add, sub) linear rewrite included."""
    assert _window_fuzz_run("tpu", seed) == _window_fuzz_run("local",
                                                             seed)


@pytest.mark.mesh
def test_noninv_window_rides_device():
    """reduceByKeyAndWindow WITHOUT invFunc recomputes each window as a
    union of batch RDDs feeding a reduce — the union-source device
    stage; every steady-state stage rides the array path."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 16, 1) for i in range(j * 13, j * 13 + 160)]
                   for j in range(4)]
        q = ssc.queueStream(batches)
        q.reduceByKeyAndWindow(operator.add, 2.0,
                               numSplits=8).collect_batches(out)
        run_batches(ssc, 4)
        kinds = _device_kinds(c)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    assert {v for k, v in kinds} == {"array"}, kinds


@pytest.mark.mesh
def test_stream_join_rides_device():
    """Per-batch stream joins expand on the device join source in
    steady state (both sides' shuffles HBM-resident)."""
    from dpark_tpu import DparkContext

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        left = [[(i % 32, i) for i in range(j * 11, j * 11 + 120)]
                for j in range(3)]
        right = [[(i % 32, i * 2) for i in range(j * 7, j * 7 + 90)]
                 for j in range(3)]
        a = ssc.queueStream(left)
        b = ssc.queueStream(right)
        a.join(b, numSplits=8) \
         .transform(lambda r: r.map(
             lambda kv: (kv[0], kv[1][0] + kv[1][1]))
             .reduceByKey(operator.add, 8)) \
         .collect_batches(out)
        run_batches(ssc, 3)
        kinds = _device_kinds(c, last_only=True)
        c.stop()
        return [sorted(v) for _, v in out], kinds

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    # steady state (the last batch's job) must be ALL device stages
    assert kinds and {v for _, v in kinds} == {"array"}, kinds


def test_state_rewrite_falls_back_on_type_error(ctx):
    """Satellite regression (r5 advisor, low): a stream whose FIRST
    batch is numeric locks the union-reduce rewrite in; a later batch
    with non-numeric values must NOT silently concatenate through the
    pairwise a+b — the checked op raises TypeError, run_batch disables
    the rewrite permanently and replays the batch through the generic
    updateFunc path (which faithfully reproduces sum()'s TypeError for
    strings, exactly like the reference)."""
    ssc = make_ssc(ctx)
    out = []
    batches = [
        [("a", 1), ("a", 2), ("b", 3)],       # numeric: probe locks in
        [("a", 1), ("a", "x"), ("b", 2)],     # poisoned tail
        [("a", 5), ("b", 1)],                  # numeric again
    ]
    q = ssc.queueStream(batches)

    def update(vs, prev):
        return (prev or 0) + sum(vs)

    state = q.updateStateByKey(update)
    state.collect_batches(out)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0

    ssc.run_batch(1001.0)
    assert dict(out[-1][1]) == {"a": 3, "b": 3}
    assert state._numeric is True             # rewrite engaged

    # poisoned batch: the rewrite falls back, and the generic path
    # reproduces the reference behavior (sum() raises for int+str)
    with pytest.raises(Exception) as ei:
        ssc.run_batch(1002.0)
    assert "TypeError" in str(ei.value) or isinstance(ei.value,
                                                      TypeError)
    assert state._numeric is False            # latched off for good

    # the stream recovers: the next numeric batch runs generically and
    # the accumulated state survived the dropped batch
    ssc.run_batch(1003.0)
    assert dict(out[-1][1]) == {"a": 8, "b": 4}


def test_window_rewrite_falls_back_on_type_error(ctx):
    """Same contract for the (add, sub) incremental window: a stream
    that defeats the 5-record probe must end up on the generic
    leftOuterJoin+invFunc path instead of silently diverging."""
    ssc = make_ssc(ctx, batch=1.0)
    out = []
    batches = [[("k", 1)], [("k", 2)], [("k", "x")], [("k", 8)]]
    q = ssc.queueStream(batches)
    q.reduceByKeyAndWindow(operator.add, 2.0,
                           invFunc=operator.sub).collect_batches(out)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0
    ssc.run_batch(1001.0)
    ssc.run_batch(1002.0)
    assert dict(out[-1][1]) == {"k": 3}
    streams = [s for s in ssc._all_streams()
               if type(s).__name__ == "ReducedWindowedDStream"]
    assert streams and streams[0]._numeric is True
    # the poisoned batch disables the rewrite; whatever error surfaces
    # is the generic path's own (str in an (add, sub) window)
    try:
        ssc.run_batch(1003.0)
    except Exception:
        pass
    assert streams[0]._numeric is False


def test_rewrite_fallback_leaves_sibling_chains_intact(ctx):
    """Fallback surgery is scoped to the FAILING output chain: an
    independent healthy state stream must keep its batch-t state (the
    code-review repro: popping generated[t] globally made the healthy
    chain silently drop a batch and regress at t+1)."""
    ssc = make_ssc(ctx)
    out_a, out_b = [], []
    qa = ssc.queueStream([[("a", 1)], [("a", 10)], [("a", 100)]])
    qb = ssc.queueStream([[("b", 1)], [("b", "x")], [("b", 5)]])

    def update(vs, prev):
        return (prev or 0) + sum(vs)

    sa = qa.updateStateByKey(update)
    sb = qb.updateStateByKey(update)
    sa.collect_batches(out_a)
    sb.collect_batches(out_b)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0

    ssc.run_batch(1001.0)
    assert dict(out_a[-1][1]) == {"a": 1}
    # chain B poisons batch 2; chain A already emitted (or still must
    # emit) its batch-2 state and MUST NOT lose it
    try:
        ssc.run_batch(1002.0)
    except Exception:
        pass
    ssc.run_batch(1003.0)
    assert dict(out_a[-1][1]) == {"a": 111}   # 1 + 10 + 100, no gap
    assert sb._numeric is False               # only B latched off
    assert sa._numeric is not False


def test_checked_op_rejects_numpy_strings():
    """np.str_ carries dtype+shape; the checked op must not let it
    slip past as an 'array-like' and concatenate (code-review)."""
    import numpy as np
    import operator
    from dpark_tpu.dstream import _CheckedNumericOp, _NumericRewriteError
    op = _CheckedNumericOp(operator.add, "add")
    assert op(2, 3) == 5
    assert op(np.int64(2), 3) == 5
    with pytest.raises(_NumericRewriteError):
        op(np.str_("a"), np.str_("b"))
    with pytest.raises(_NumericRewriteError):
        op(1, "x")


def test_general_traceable_updatestate_rides_device():
    """A decayed-counter updateFunc — traceable but NOT a provable
    monoid fold — rewrites to flag-union + groupByKey + the state-mode
    SegMapOp: in steady state every stage rides the array path (state
    lives as HBM-resident columns, the per-batch cogroup and the
    vmapped update(prev, values) run on device), with values matching
    the local master.  The `prev is None` spelling is the traceable
    form (the dual trace sees the literal None); `prev or 0` forces a
    tracer bool and keeps the cogroup path."""
    from dpark_tpu import DparkContext

    def update(vs, prev):
        base = 0.0 if prev is None else prev
        return base * 0.9 + sum(vs)

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 11, (i * 3) % 7) for i in range(j * 13,
                                                         j * 13 + 250)]
                   for j in range(5)]
        q = ssc.queueStream(batches)
        q.updateStateByKey(update, numSplits=8).collect_batches(out)
        run_batches(ssc, 5)
        kinds = []
        for rec in c.scheduler.history:
            for st in rec.get("stage_info", ()):
                if st.get("kind") is not None:
                    kinds.append((st.get("rdd"), st["kind"]))
        c.stop()
        return ([sorted((int(k), round(float(v), 6)) for k, v in vals)
                 for _, vals in out], kinds)

    got, kinds = drive("tpu")
    exp, _ = drive("local")
    assert got == exp
    # steady state: the union map stage AND the grouped-update reduce
    # stage are all-array
    steady = [v for _, v in kinds[-4:]]
    assert set(steady) == {"array"}, kinds


# ---------------------------------------------------------------------------
# pane-tree windowing (ISSUE 10): parity suite + unit tests
# ---------------------------------------------------------------------------

def _pane_conf(monkeypatch, on):
    from dpark_tpu import conf
    monkeypatch.setattr(conf, "STREAM_PANES", on)


def _drive_window(master, batches, window, slide=None, invFunc=None,
                  func=operator.add, eventTime=None, lateness=None,
                  keep=None):
    """Run one windowed stream over queued batches with the manual
    clock; returns ([(t, sorted(values))], the stream, the context)."""
    from dpark_tpu import DparkContext
    c = DparkContext(master)
    ssc = make_ssc(c, batch=1.0)
    out = []
    q = ssc.queueStream([list(b) for b in batches])
    s = q.reduceByKeyAndWindow(func, float(window), slide,
                               invFunc=invFunc, eventTime=eventTime,
                               lateness=lateness)
    s.collect_batches(out)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0
    for k in range(1, len(batches) + 1):
        ssc.run_batch(1000.0 + k)
    res = [(t, sorted(v)) for t, v in out]
    if keep is not None:
        keep.extend([ssc, s])
    c.stop()
    return res


def _fuzz_batches(seed, nb, empties=True):
    import random
    rng = random.Random(seed)
    batches = []
    for _ in range(nb):
        if empties and rng.random() < 0.2:
            batches.append([])
        else:
            batches.append([(rng.randint(0, 9), rng.randint(-9, 9))
                            for _ in range(rng.randint(1, 80))])
    return batches


@pytest.mark.parametrize("window,slide", [(4, None), (8, None),
                                          (4, 2.0), (6, 3.0)])
def test_pane_parity_invertible(monkeypatch, window, slide):
    """Invertible pane path bit-identical to the pre-pane per-batch
    path across window/slide shapes (incl. slide > batch and empty
    micro-batches)."""
    batches = _fuzz_batches(101 + window, 14)
    _pane_conf(monkeypatch, True)
    got = _drive_window("local", batches, window, slide,
                        invFunc=operator.sub)
    _pane_conf(monkeypatch, False)
    exp = _drive_window("local", batches, window, slide,
                        invFunc=operator.sub)
    assert got == exp
    assert got, "no windows emitted"


@pytest.mark.parametrize("window", [4, 8, 16])
def test_pane_parity_noninvertible(monkeypatch, window):
    """Non-invertible pane tree (classified add monoid) bit-identical
    to the whole-window recompute — integer values, so the tree's
    re-association is exact."""
    batches = _fuzz_batches(7 + window, window + 8)
    _pane_conf(monkeypatch, True)
    got = _drive_window("local", batches, window)
    _pane_conf(monkeypatch, False)
    exp = _drive_window("local", batches, window)
    assert got == exp


def test_pane_parity_counter_generic_inv(monkeypatch):
    """Counter values defeat the numeric probe on BOTH sides; the pane
    path's generic invFunc branch (one aggregate-pane inverse join)
    must match the per-batch joins."""
    from collections import Counter
    batches = [[("k", Counter(a=1, b=j))] for j in range(8)]
    _pane_conf(monkeypatch, True)
    got = _drive_window("local", batches, 3.0, invFunc=operator.sub)
    _pane_conf(monkeypatch, False)
    exp = _drive_window("local", batches, 3.0, invFunc=operator.sub)
    assert got == exp


def test_pane_chaos_parity(monkeypatch):
    """Pane state survives DPARK_FAULTS injection bit-identically:
    panes are cached reduced RDDs, so a failed fetch recovers through
    the standard shuffle planes (lineage here; coded decode when a
    code is active) — never a whole-window recompute or a wrong
    answer."""
    from dpark_tpu import faults
    batches = _fuzz_batches(55, 12, empties=False)
    _pane_conf(monkeypatch, True)
    faults.configure(None)
    try:
        clean_inv = _drive_window("local", batches, 6.0,
                                  invFunc=operator.sub)
        clean_tree = _drive_window("local", batches, 8.0)
        # `times` bounds total firings (the chaos-suite idiom): an
        # unbounded p=0.2 across a long stream's many fetch retries
        # can legitimately exhaust MAX_STAGE_FAILURES
        faults.configure("shuffle.fetch:p=0.2,seed=7,times=6")
        chaos_inv = _drive_window("local", batches, 6.0,
                                  invFunc=operator.sub)
        faults.configure("shuffle.fetch:p=0.2,seed=7,times=6")
        chaos_tree = _drive_window("local", batches, 8.0)
    finally:
        faults.configure(None)
    assert chaos_inv == clean_inv
    assert chaos_tree == clean_tree


def test_pane_invertible_constant_branches(monkeypatch):
    """The O(1) claim, structurally: the steady-state window update is
    ONE union-reduce whose branch count does not depend on the
    window/slide ratio (prev + new pane - expired pane)."""
    from dpark_tpu.rdd import UnionRDD
    _pane_conf(monkeypatch, True)

    def steady_branches(window):
        keep = []
        batches = [[(i % 5, 1) for i in range(30)]
                   for _ in range(window + 4)]
        _drive_window("local", batches, float(window),
                      invFunc=operator.sub, keep=keep)
        ssc, s = keep
        last = s.generated[max(s.generated)]
        # the emitted rdd is reduce(union(...)): walk to the union
        src = last
        while src is not None and not isinstance(src, UnionRDD):
            deps = getattr(src, "dependencies", [])
            src = deps[0].rdd if deps else None
        assert src is not None, "no union under the window update"
        return len(src.rdds)

    b4, b16 = steady_branches(4), steady_branches(16)
    assert b4 == b16 == 3, (b4, b16)


def test_pane_tree_log_branches(monkeypatch):
    """The O(log w) claim, structurally: a non-invertible w-pane
    window emits a union of at most ~2*log2(w) merge-tree branches,
    not w."""
    import math
    from dpark_tpu.rdd import UnionRDD
    _pane_conf(monkeypatch, True)
    w = 16
    keep = []
    batches = [[(i % 5, 1) for i in range(30)] for _ in range(w + 6)]
    _drive_window("local", batches, float(w), keep=keep)
    ssc, s = keep
    assert type(s).__name__ == "PanedWindowReduceDStream"
    assert s._use_tree is True
    last = s.generated[max(s.generated)]
    src = last
    while src is not None and not isinstance(src, UnionRDD):
        deps = getattr(src, "dependencies", [])
        src = deps[0].rdd if deps else None
    assert src is not None
    assert len(src.rdds) <= 2 * math.log2(w) + 2 < w, len(src.rdds)
    # amortized O(1) node builds per pane over the whole run
    assert s._tree.builds <= len(batches) + w


def test_dyadic_blocks_cover_and_reuse():
    """dyadic_blocks: exact cover, aligned power-of-two blocks, and
    block reuse across consecutive windows (the cache hit substrate)."""
    from dpark_tpu.panes import dyadic_blocks
    for lo, hi in [(0, 0), (0, 15), (5, 12), (7, 38), (31, 32)]:
        blocks = dyadic_blocks(lo, hi)
        covered = []
        for start, size in blocks:
            assert size & (size - 1) == 0
            assert start % size == 0
            covered.extend(range(start, start + size))
        assert covered == list(range(lo, hi + 1)), (lo, hi, blocks)
    # blocks are ALIGNED, so the block set over a whole sliding run is
    # bounded: every block any 16-pane window over 64 panes needs is
    # built once — amortized O(1) builds per pane
    seen = set()
    for lo in range(0, 48):
        seen.update(dyadic_blocks(lo, lo + 15, max_size=8))
    builds = sum(1 for _, size in seen if size > 1)
    assert builds <= 64, builds        # vs 48 windows * 15 re-merges


def test_merge_tree_invalidate_rebuilds_only_covering_nodes():
    from dpark_tpu.panes import MergeTree
    panes = {i: ["p%d" % i] for i in range(8)}
    merged = []

    def merge(kids, size, start):
        merged.append((start, size))
        out = []
        for k in kids:
            out.extend(k)
        return out

    tree = MergeTree(panes.get, merge)
    cover = tree.cover(0, 7)
    assert sorted(x for blk in cover for x in blk) == \
        sorted(x for v in panes.values() for x in v)
    n_first = len(merged)
    tree.cover(0, 7)                   # fully cached: no new merges
    assert len(merged) == n_first
    tree.invalidate(3)                 # dirties (2,2), (0,4), (0,8)...
    tree.cover(0, 7)
    rebuilt = merged[n_first:]
    assert rebuilt and len(rebuilt) <= 3, rebuilt
    assert all(start <= 3 < start + size or size <= 4
               for start, size in rebuilt)


def test_pane_event_time_late_patch_and_drop(monkeypatch):
    """Event-time windows: a late record inside the allowed lateness
    patches ONLY its pane (the window fold picks it up); a record
    below the watermark drops and is counted.  Values ARE the event
    timestamps (eventTime = itemgetter(1)), so expectations are exact
    sums of admitted timestamps."""
    _pane_conf(monkeypatch, True)
    ts = lambda k: 1000.0 + k  # noqa: E731  (readability)
    batches = [
        [("k", ts(1))],
        [("k", ts(2))],
        [("k", ts(3)), ("k", ts(1))],     # late by 2 panes: admitted
        [("k", ts(4)), ("k", ts(0.5))],   # below watermark: dropped
    ]
    keep = []
    got = _drive_window(
        "local", batches, 4.0, invFunc=operator.sub,
        eventTime=operator.itemgetter(1), lateness=2.0, keep=keep)
    ssc, s = keep
    vals = [v for _, v in got]
    # window 4 covers everything admitted so far each tick
    assert vals[0] == [("k", ts(1))]
    assert vals[1] == [("k", ts(1) + ts(2))]
    # tick 3: on-time ts(3) plus the late ts(1) patched into pane 1
    assert vals[2] == [("k", ts(1) + ts(2) + ts(3) + ts(1))]
    # tick 4: ts(0.5) < watermark (max 1003 - lateness 2.0) drops
    assert vals[3] == [("k", ts(1) + ts(2) + ts(3) + ts(1) + ts(4))]
    assert s._stats["late_patches"] == 1
    assert s._stats["late_patched_rows"] == 1
    assert s._stats["late_dropped"] == 1
    assert s._stats["watermark"] == ts(4) - 2.0
    assert s._stats["watermark_lag_s"] is not None


def test_pane_event_time_noninv_tree_patch(monkeypatch):
    """Late patches under the merge tree: only the nodes covering the
    patched pane rebuild, and the emitted window folds the patch."""
    from dpark_tpu import conf
    _pane_conf(monkeypatch, True)
    monkeypatch.setattr(conf, "STREAM_PANE_TREE_MIN", 4)
    n = 10
    batches = [[("k", 1000.0 + j + 1)] for j in range(n)]
    batches[6].append(("k", 1000.0 + 3))      # late by 4 panes
    keep = []
    got = _drive_window("local", batches, 8.0,
                        eventTime=operator.itemgetter(1), lateness=8.0,
                        keep=keep)
    ssc, s = keep
    assert type(s).__name__ == "PanedWindowReduceDStream"
    assert s._stats["late_patches"] == 1
    # tick 7 window (panes 1..7 of ts 1001..1007) includes the patch
    exp7 = sum(1000.0 + k for k in range(1, 8)) + 1003.0
    assert got[6][1] == [("k", exp7)]


def test_pane_late_buffer_bound(monkeypatch):
    """conf.STREAM_LATE_BUFFER_ROWS: an oversized late patch drops
    whole (deterministically) and is counted."""
    from dpark_tpu import conf
    _pane_conf(monkeypatch, True)
    monkeypatch.setattr(conf, "STREAM_LATE_BUFFER_ROWS", 2)
    batches = [
        [("k", 1000.0 + 1)],
        [("k", 1000.0 + 2)] + [("k", 1000.0 + 1)] * 3,  # 3 late > cap 2
    ]
    keep = []
    got = _drive_window(
        "local", batches, 4.0, invFunc=operator.sub,
        eventTime=operator.itemgetter(1), lateness=4.0, keep=keep)
    ssc, s = keep
    assert s._stats["late_dropped"] == 3
    assert s._stats["late_patches"] == 0
    assert got[1][1] == [("k", 1000.0 + 1 + 1000.0 + 2)]


def test_window_noninv_fallback_marks_plan(monkeypatch):
    """A non-invertible window op with NO registered merge keeps the
    O(w) path and the window-noninv-no-merge lint rule explains it;
    __dpark_window_merge__ opts an equivalent op back into the pane
    tree."""
    from dpark_tpu.analysis import lint_plan
    _pane_conf(monkeypatch, True)

    def weird(a, b):
        return a + b - 0          # not a classified monoid bytecode

    keep = []
    batches = [[("k", j)] for j in range(6)]
    got = _drive_window("local", batches, 4.0, func=weird, keep=keep)
    ssc, s = keep
    assert type(s).__name__ == "TransformedDStream"
    last = s.generated[max(s.generated)]
    assert getattr(last, "_window_noninv", None)
    report = lint_plan(last)
    assert any(f.rule == "window-noninv-no-merge" for f in report)
    # user assertion opts back in
    weird.__dpark_window_merge__ = True
    keep2 = []
    got2 = _drive_window("local", batches, 4.0, func=weird, keep=keep2)
    assert type(keep2[1]).__name__ == "PanedWindowReduceDStream"
    assert got2 == got


def test_slide_cadence_gating(monkeypatch):
    """A windowed stream with slide > batch emits only at slide
    multiples (reference semantics) — on both the pane and the
    per-batch paths."""
    batches = [[("k", 1)] for _ in range(8)]
    for on in (True, False):
        _pane_conf(monkeypatch, on)
        got = _drive_window("local", batches, 4.0, 2.0,
                            invFunc=operator.sub)
        assert [t for t, _ in got] == [1002.0, 1004.0, 1006.0, 1008.0]
        assert [v for _, v in got] == [[("k", 2)], [("k", 4)],
                                       [("k", 4)], [("k", 4)]]


def test_pane_stage_attribution_and_stats(monkeypatch):
    """Stage records carry the pane-plane stream tags (schedule.py
    seam) and the panes registry feeds /api/streams + the /metrics
    stream gauges."""
    from dpark_tpu import DparkContext, panes
    from dpark_tpu.web import render_metrics
    _pane_conf(monkeypatch, True)
    c = DparkContext("local")
    ssc = make_ssc(c, batch=1.0)
    out = []
    q = ssc.queueStream([[(i % 4, 1) for i in range(40)]
                         for _ in range(6)])
    win = q.reduceByKeyAndWindow(operator.add, 3.0,
                                 invFunc=operator.sub)
    win.collect_batches(out)
    run_batches(ssc, 6)
    roles = set()
    for rec in c.scheduler.history:
        for st in rec.get("stage_info", ()):
            tag = st.get("stream")
            if tag:
                roles.add(tag["role"])
    assert "window-emit" in roles, roles
    sid = win._sid
    st = panes.stream_stats().get(sid)
    assert st, "stream not registered"
    assert st["panes"] >= 1 and st["ticks"] == 6
    text = render_metrics(c.scheduler)
    assert 'dpark_stream_panes{stream="%s"}' % sid in text
    assert "dpark_stream_late_dropped_total" in text
    ssc.stop()
    assert sid not in panes.stream_stats()   # registry cleaned up
    c.stop()


def test_checked_op_type_verdict_cache():
    """ISSUE 10 satellite: the per-pair re-verification memoizes per
    (class, dtype kind) — an int array must not pre-approve a string
    array, and strings still raise after numerics cached."""
    import numpy as np
    from dpark_tpu.dstream import _CheckedNumericOp, _NumericRewriteError
    op = _CheckedNumericOp(operator.add, "add")
    assert op(1, 2) == 3
    key = (int, None)
    assert _CheckedNumericOp._TYPE_VERDICTS[key] is True
    assert (op(np.array([1, 2]), np.array([3, 4])) ==
            np.array([4, 6])).all()
    with pytest.raises(_NumericRewriteError):
        op(np.array(["a"]), np.array(["b"]))
    with pytest.raises(_NumericRewriteError):
        op(1, "x")


def test_numeric_verdict_probe_cache():
    """The probe verdict caches per (op, value type); mixed samples
    never cache a stale verdict for the head type."""
    from dpark_tpu import dstream as ds
    ds._PROBE_VERDICTS.clear()
    assert ds._numeric_verdict("add", [1, 2, 3]) is True
    assert ds._PROBE_VERDICTS[("add", int)] is True
    assert ds._numeric_verdict("add", ["a", "b"]) is False
    # mixed: computed fresh, and the cached int verdict is untouched
    assert ds._numeric_verdict("add", [1, "x"]) is False
    assert ds._PROBE_VERDICTS[("add", int)] is True


def test_file_stream_arrival_stamp(ctx, tmp_path):
    """stamp_arrival: (arrival_ts, line) records with non-decreasing
    driver-clock stamps (the documented clock contract)."""
    d = tmp_path / "stamped"
    d.mkdir()
    ssc = make_ssc(ctx)
    out = []
    s = ssc.textFileStream(str(d), stamp_arrival=True)
    s.collect_batches(out)
    ssc.ctx.start()
    s.start()
    ssc.zero_time = 0.0
    t0 = time.time()
    (d / "a.txt").write_text("l1\nl2\n")
    ssc.run_batch(1.0)
    (d / "b.txt").write_text("l3\n")
    ssc.run_batch(2.0)
    recs = [r for _, v in out for r in v]
    assert [line for _, line in recs] == ["l1", "l2", "l3"]
    stamps = [ts for ts, _ in recs]
    assert all(isinstance(ts, float) and ts >= t0 for ts in stamps)
    assert stamps == sorted(stamps)
    assert stamps[0] == stamps[1]      # one scan, one timestamp


def test_socket_stream_arrival_stamp(ctx):
    import socket
    import threading
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]

    def serve():
        conn, _ = server.accept()
        conn.sendall(b"a\nb\n")
        time.sleep(1.0)
        conn.close()

    threading.Thread(target=serve, daemon=True).start()
    ssc = make_ssc(ctx, batch=0.2)
    out = []
    s = ssc.socketTextStream("127.0.0.1", port, stamp_arrival=True)
    s.collect_batches(out)
    t0 = time.time()
    ssc.start()
    deadline = time.time() + 8
    while sum(len(v) for _, v in out) < 2 and time.time() < deadline:
        time.sleep(0.05)
    ssc.stop()
    server.close()
    recs = [r for _, v in out for r in v]
    assert [line for _, line in recs] == ["a", "b"]
    assert all(isinstance(ts, float) and ts >= t0 for ts, _ in recs)


def test_pane_checkpoint_state_prunes(monkeypatch, ctx, tmp_path):
    """The metadata snapshot keeps only checkpointed panes (same
    contract as `generated`) and a recovered pane stream re-registers
    and keeps answering."""
    from dpark_tpu import serialize
    _pane_conf(monkeypatch, True)
    ssc = make_ssc(ctx)
    out = []
    q = ssc.queueStream([[("k", j)] for j in range(5)])
    s = q.reduceByKeyAndWindow(operator.add, 3.0, invFunc=operator.sub)
    s.collect_batches(out)
    run_batches(ssc, 5)
    blob = serialize.dumps(s.__getstate__())
    state = serialize.loads(blob)
    assert state["_panes"] == {}       # nothing checkpointed: pruned
    assert state["_sid"] is None and state["_stats"] is None


def test_untraceable_updatestate_keeps_cogroup_parity():
    """An updateFunc with data-dependent Python control flow cannot
    trace: the classification declines and the cogroup path answers —
    identical on both masters (including eviction via None)."""
    from dpark_tpu import DparkContext

    def update(vs, prev):
        total = (prev if prev is not None else 0) + sum(vs)
        if total > 40:                  # tracer-unsafe branch + evict
            return None
        return total

    def drive(master):
        c = DparkContext(master)
        ssc = make_ssc(c, batch=1.0)
        out = []
        batches = [[(i % 5, i % 4) for i in range(j * 7, j * 7 + 40)]
                   for j in range(4)]
        q = ssc.queueStream(batches)
        q.updateStateByKey(update, numSplits=4).collect_batches(out)
        run_batches(ssc, 4)
        c.stop()
        return [sorted((int(k), int(v)) for k, v in vals)
                for _, vals in out]

    assert drive("tpu") == drive("local")
