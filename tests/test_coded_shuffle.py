"""Coded shuffle (ISSUE 6): erasure-coded exchange that survives
faults and stragglers with ZERO lineage recompute.

Two layers of proof:

* codec property tests — GF(2^8) Reed–Solomon and systematic XOR
  encode/decode over arbitrary payloads: ANY m erasures recoverable,
  m+1 not, numpy and pure-Python paths bit-identical;
* the chaos matrix — {xor, rs(4,2)} x {fetch fault p=0.2, spill
  corruption, straggler delay} x {host path, device ``hbm://`` path},
  every cell asserting bit-identical results with
  ``resubmits == recomputes == 0`` and decode counters > 0 — the same
  injections that cost PR 5's lineage path a full resubmit round now
  cost one decode.

Device tests run on a 2-device sliced mesh ("tpu:2") so the suite
works on small containers."""

import itertools
import operator
import os

import numpy as np
import pytest

from dpark_tpu import coding, conf, faults
from dpark_tpu.coding import Code, ShardShortfall, parse_code
from dpark_tpu.shuffle import (FetchFailed, LocalFileShuffle,
                               SpillCorruption, read_bucket_any)


@pytest.fixture(autouse=True)
def _clean_planes():
    """Every test starts and ends with no chaos plane, no shuffle
    code, and fresh decode counters."""
    faults.configure(None)
    coding.configure(None)
    coding.reset_counters()
    yield
    faults.configure(None)
    coding.configure(None)
    coding.reset_counters()


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


@pytest.fixture()
def tiny_waves():
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 500
    yield
    conf.STREAM_CHUNK_ROWS = old


# ---------------------------------------------------------------------------
# codec: grammar + GF(2^8) properties
# ---------------------------------------------------------------------------

def test_parse_code_grammar():
    assert parse_code("off") is None
    assert parse_code("") is None
    assert parse_code(None) is None
    assert parse_code("xor").describe() == "xor(4)"
    assert parse_code("xor(8)").describe() == "xor(8)"
    assert parse_code("rs(4,2)").describe() == "rs(4,2)"
    assert parse_code("RS(10, 4)").describe() == "rs(10,4)"
    for bad in ("xr", "rs(4)", "rs(4,2,1)", "xor(a)", "rs"):
        with pytest.raises(ValueError):
            parse_code(bad)


def test_code_rejects_bad_geometry():
    with pytest.raises(ValueError):
        Code(coding.ALGO_RS, 0, 1)
    with pytest.raises(ValueError):
        Code(coding.ALGO_RS, 4, 0)
    with pytest.raises(ValueError):
        Code(coding.ALGO_XOR, 4, 2)         # xor is single-loss only
    with pytest.raises(ValueError):
        Code(coding.ALGO_RS, 200, 60)       # k+m > 255 over GF(2^8)


def test_gf_field_axioms():
    from dpark_tpu.coding import gf_inv, gf_mul
    assert gf_mul(0, 77) == 0 and gf_mul(77, 1) == 77
    for a in (1, 2, 37, 129, 255):
        assert gf_mul(a, gf_inv(a)) == 1
    # commutativity + a distributivity spot check (xor is addition)
    assert gf_mul(23, 99) == gf_mul(99, 23)
    assert gf_mul(7, 12 ^ 200) == gf_mul(7, 12) ^ gf_mul(7, 200)


PAYLOADS = [b"", b"x", b"abcdef", bytes(range(256)) * 3 + b"tail",
            os.urandom(1031)]


@pytest.mark.parametrize("spec", ["xor", "xor(3)", "rs(4,2)",
                                  "rs(5,3)"])
def test_any_m_erasures_recoverable(spec):
    """The MDS property: EVERY k-subset of the n shards reconstructs
    the payload exactly (so any m erasures are survivable)."""
    code = parse_code(spec)
    for blob in PAYLOADS:
        shards = code.encode(blob)
        assert len(shards) == code.n
        for keep in itertools.combinations(range(code.n), code.k):
            have = {i: shards[i] for i in keep}
            assert code.decode(have, len(blob)) == blob, (spec, keep)


@pytest.mark.parametrize("spec", ["xor", "rs(4,2)"])
def test_m_plus_one_erasures_unrecoverable(spec):
    code = parse_code(spec)
    blob = bytes(range(200))
    shards = code.encode(blob)
    have = {i: shards[i] for i in range(code.k - 1)}    # k-1 survive
    with pytest.raises(ShardShortfall) as e:
        code.decode(have, len(blob))
    assert e.value.found == code.k - 1
    assert e.value.needed == code.k


def test_pure_python_fallback_matches_numpy():
    """The numpy-vectorized GF path and the table-driven pure-Python
    path produce IDENTICAL shards and decodes."""
    code = parse_code("rs(4,2)")
    blob = os.urandom(513)
    fast = code.encode(blob)
    coding._FORCE_PURE = True
    try:
        slow = code.encode(blob)
        assert fast == slow
        have = {i: slow[i] for i in (1, 2, 4, 5)}       # 2 data lost
        assert code.decode(have, len(blob)) == blob
    finally:
        coding._FORCE_PURE = False


def test_shard_frame_crc_detects_corruption():
    from dpark_tpu.coding import ShardCorrupt, pack_shard, unpack_shard
    code = parse_code("rs(4,2)")
    frame = pack_shard(code, 3, 100, b"payload-bytes")
    fr = unpack_shard(frame)
    assert (fr.idx, fr.orig_len, fr.payload) == (3, 100,
                                                 b"payload-bytes")
    bad = bytearray(frame)
    bad[-4] ^= 0xFF                         # flip a payload byte
    with pytest.raises(ShardCorrupt):
        unpack_shard(bytes(bad))


def test_container_decodes_around_corruption():
    """A shard container with one corrupted region loses exactly the
    shards the corruption touched and decodes from the rest — counted
    as a repair; past m corrupted shards only ShardShortfall is
    left."""
    code = parse_code("rs(4,2)")
    blob = os.urandom(4096)
    raw = coding.encode_container(blob, code)
    assert coding.is_container(raw)
    assert coding.decode_container(raw) == blob
    # corrupt one shard's payload (inside the body, past both headers)
    bad = bytearray(raw)
    bad[len(bad) // 2] ^= 0xFF
    coding.reset_counters()
    assert coding.decode_container(bytes(bad)) == blob
    assert coding.counters_snapshot()["totals"]["repair"] == 1
    # corrupt every shard region: information-theoretically gone
    faults.configure("shuffle.spill_read:p=1,kind=corrupt")
    with pytest.raises(ShardShortfall):
        coding.decode_container(raw, fault_site="shuffle.spill_read")


# ---------------------------------------------------------------------------
# chaos matrix: host path
# ---------------------------------------------------------------------------

def _reduce_job(ctx):
    return sorted(ctx.parallelize([(i % 7, i) for i in range(210)], 4)
                  .reduceByKey(operator.add, 3).collect())


def _group_job(ctx):
    return sorted(
        ctx.parallelize([(i % 150, i % 5) for i in range(600)], 4)
        .groupByKey(3).mapValue(lambda vs: tuple(sorted(vs)))
        .collect())


def _assert_zero_recompute(rec):
    assert rec["state"] == "done"
    assert rec.get("resubmits", 0) == 0, rec
    assert rec.get("recomputes", 0) == 0, rec


@pytest.mark.parametrize("mode", ["xor", "rs(4,2)"])
def test_host_fetch_fault_decodes_not_recomputes(ctx, mode):
    """The ISSUE 6 chaos proof, host path: the same seeded fetch
    injection that costs the uncoded path a parent-stage resubmit
    round completes with ZERO resubmits/recomputes — the failed shard
    is decoded from parity (repair counter > 0)."""
    clean = _reduce_job(ctx)
    coding.configure(mode)
    coding.reset_counters()
    faults.configure("shuffle.fetch:p=0.2,seed=7")
    assert _reduce_job(ctx) == clean
    rec = ctx.scheduler.history[-1]
    _assert_zero_recompute(rec)
    assert rec["decodes"]["repair"] > 0, rec["decodes"]
    assert rec["decodes"]["mode"] == coding.describe()
    assert faults.stats()["shuffle.fetch"]["fired"] > 0
    # per-stage attribution: the decoded shuffle's PARENT stage row
    assert any((st.get("decodes") or {}).get("repair", 0) > 0
               for st in rec["stage_info"]), rec["stage_info"]


@pytest.mark.parametrize("mode", ["xor", "rs(4,2)"])
def test_host_spill_corruption_decodes_not_recomputes(ctx, mode):
    """A corrupted host spill chunk (DiskSpillMerger) loses one shard
    INSIDE the coded container and is decoded around — where the
    uncoded path pays an intact-parent task recompute."""
    old = conf.SHUFFLE_CHUNK_RECORDS
    conf.SHUFFLE_CHUNK_RECORDS = 8          # max_items 32: force spills
    try:
        clean = _group_job(ctx)
        coding.configure(mode)
        coding.reset_counters()
        faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
        assert _group_job(ctx) == clean
        rec = ctx.scheduler.history[-1]
        _assert_zero_recompute(rec)
        assert faults.stats()["shuffle.spill_write"]["fired"] == 1
        assert rec["decodes"]["repair"] > 0, rec["decodes"]
    finally:
        conf.SHUFFLE_CHUNK_RECORDS = old


@pytest.mark.parametrize("mode", ["xor", "rs(4,2)"])
def test_host_straggler_delay_fastest_k_wins(ctx, mode):
    """kind=delay slows a random subset of shard fetches; the decode
    proceeds from the fastest k (straggler_win counter) with zero
    recovery events — the case speculation only partially covers."""
    clean = _reduce_job(ctx)
    coding.configure(mode)
    coding.reset_counters()
    faults.configure("shuffle.fetch:p=0.3,seed=3,kind=delay,ms=150")
    assert _reduce_job(ctx) == clean
    rec = ctx.scheduler.history[-1]
    _assert_zero_recompute(rec)
    d = rec["decodes"]
    assert d["straggler_win"] > 0, d
    assert d["decode_failures"] == 0, d


# ---------------------------------------------------------------------------
# chaos matrix: device hbm:// path
# ---------------------------------------------------------------------------

def _device_group_job(tctx2):
    """Map side on the device (hbm:// shuffle store), consume through
    the host fetch path — every bucket crosses the export bridge as
    framed erasure shards.  Needs the `tiny_waves` fixture: at stock
    wave budgets this groupByKey declines the array path entirely and
    the test would silently duplicate the host matrix."""
    from dpark_tpu import Columns
    keys = np.arange(15000, dtype=np.int64) % 97
    vals = np.arange(15000, dtype=np.int64) % 13
    return {k: sorted(v) for k, v in
            tctx2.parallelize(Columns(keys, vals), 2)
            .groupByKey(8).collect()}


def _assert_device_parent(rec):
    """The map stage must actually have ridden the array path (hbm://
    outputs) — otherwise the 'device' chaos cell proves nothing the
    host cell didn't."""
    kinds = [st.get("kind") or "" for st in rec["stage_info"]]
    assert any(k.startswith("array") for k in kinds), kinds


def _join_premergers(ex):
    """Wait out background premerge walkers from PREVIOUS runs so a
    freshly configured chaos plane cannot be consumed by a stale
    store's merged-run writes."""
    for s in list(ex.shuffle_store.values()):
        pm = s.get("premerge")
        if pm is not None and pm._thread is not None:
            pm._thread.join(timeout=10)


@pytest.mark.parametrize("mode", ["xor", "rs(4,2)"])
def test_device_fetch_fault_decodes_not_recomputes(tctx2, tiny_waves,
                                                   mode):
    """The ISSUE 6 chaos proof, device path: under PR 5's rules a
    failed hbm:// fetch invalidated ALL of the device parent's outputs
    (one fault = a full stage resubmit).  With coding on, the lost
    shard decodes from parity and the parent never re-runs."""
    clean = _device_group_job(tctx2)
    _join_premergers(tctx2.scheduler.executor)
    coding.configure(mode)
    coding.reset_counters()
    faults.configure("shuffle.fetch:p=0.2,seed=7")
    assert _device_group_job(tctx2) == clean
    rec = tctx2.scheduler.history[-1]
    _assert_device_parent(rec)
    _assert_zero_recompute(rec)
    assert rec["decodes"]["repair"] > 0, rec["decodes"]
    assert faults.stats()["shuffle.fetch"]["fired"] > 0


@pytest.mark.parametrize("mode", ["xor", "rs(4,2)"])
def test_device_spill_corruption_decodes_not_recomputes(
        tctx2, tiny_waves, mode):
    """A corrupted device spill RUN (the streamed no-combine path)
    previously invalidated the whole parent device stage; the coded
    container decodes around the corrupted shard instead."""
    clean = _device_group_job(tctx2)
    _join_premergers(tctx2.scheduler.executor)
    coding.configure(mode)
    coding.reset_counters()
    faults.configure("shuffle.spill_write:nth=3,kind=corrupt")
    assert _device_group_job(tctx2) == clean
    rec = tctx2.scheduler.history[-1]
    _assert_device_parent(rec)
    _assert_zero_recompute(rec)
    assert faults.stats()["shuffle.spill_write"]["fired"] == 1
    # spill-run decodes aren't shuffle-attributed; totals carry them
    assert coding.counters_snapshot()["totals"]["repair"] > 0


@pytest.mark.parametrize("mode", ["xor", "rs(4,2)"])
def test_device_straggler_delay_fastest_k_wins(tctx2, tiny_waves,
                                               mode):
    clean = _device_group_job(tctx2)
    _join_premergers(tctx2.scheduler.executor)
    coding.configure(mode)
    coding.reset_counters()
    faults.configure("shuffle.fetch:p=0.3,seed=3,kind=delay,ms=150")
    assert _device_group_job(tctx2) == clean
    rec = tctx2.scheduler.history[-1]
    _assert_device_parent(rec)
    _assert_zero_recompute(rec)
    assert rec["decodes"]["straggler_win"] > 0, rec["decodes"]


# ---------------------------------------------------------------------------
# executor spill runs: coded container round trip
# ---------------------------------------------------------------------------

def test_executor_run_container_round_trip(tmp_path):
    from dpark_tpu.backend.tpu.executor import JAXExecutor
    coding.configure("rs(4,2)")
    p = str(tmp_path / "run")
    cols = [np.arange(100, dtype=np.int64), np.ones(100)]
    JAXExecutor._write_run(p, cols)
    with open(p, "rb") as f:
        assert coding.is_container(f.read())
    # a corrupted write decodes around the lost shard at read
    faults.configure("shuffle.spill_write:nth=1,kind=corrupt")
    JAXExecutor._write_run(p, cols)
    back = JAXExecutor._read_run(p)
    assert np.array_equal(back[0], cols[0])
    assert coding.counters_snapshot()["totals"]["repair"] >= 1
    # every shard corrupted: SpillCorruption (lineage), not garbage
    faults.configure("shuffle.spill_write:p=1,kind=corrupt")
    JAXExecutor._write_run(p, cols)
    faults.configure(None)
    with pytest.raises(SpillCorruption, match="shards survived"):
        JAXExecutor._read_run(p)


# ---------------------------------------------------------------------------
# satellites: dedup, FetchFailed fields, decode_failures accounting
# ---------------------------------------------------------------------------

def test_read_bucket_any_dedups_replica_uris(ctx):
    """A duplicated replica uri costs ONE attempt, not two — the chaos
    site's hit counter is the per-attempt ground truth."""
    faults.configure("shuffle.fetch:nth=999")       # count, never fire
    missing = "file:///no-such-dpark-workdir"
    with pytest.raises(FetchFailed):
        read_bucket_any([missing, missing, missing], 1234, 0, 0)
    assert faults.stats()["shuffle.fetch"]["hits"] == 1


def test_failed_decode_carries_shard_counts(ctx):
    """Fewer than k surviving shards: FetchFailed names how close the
    decode came (shards_found/shards_needed) and recovery_summary()
    counts it under decodes.decode_failures, distinct from the plain
    fetch_failed counter."""
    ctx.start()                     # scheduler owns recovery_summary
    coding.configure("rs(4,2)")
    uri = LocalFileShuffle.write_buckets(777, 0, [[(1, 2)]])
    path = LocalFileShuffle.get_output_file(777, 0, 0) + ".shards"
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    for fr in coding.parse_container(bytes(raw)):
        if fr.idx in (0, 2, 4):             # 3 of 6 lost: k=4 short
            raw[fr.end - 1] ^= 0xFF         # flip a payload byte
    with open(path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(FetchFailed) as e:
        read_bucket_any(uri, 777, 0, 0)
    assert e.value.shards_found == 3
    assert e.value.shards_needed == 4
    assert "decode failed: 3 of 4 shards" in str(e.value)
    summary = ctx.scheduler.recovery_summary()
    assert summary["decodes"]["decode_failures"] == 1
    assert summary["decodes"]["mode"] == "rs(4,2)"


def test_uncoded_bucket_read_back_after_enabling_code(ctx):
    """A bucket written BEFORE the code was configured still reads:
    the k-of-n probe reports a clean miss everywhere and the fetch
    falls back to the plain bucket protocol."""
    uri = LocalFileShuffle.write_buckets(778, 0, [[(5, 9)]])
    coding.configure("rs(4,2)")
    assert read_bucket_any(uri, 778, 0, 0) == [(5, 9)]
    assert coding.counters_snapshot()["totals"]["decode_failures"] == 0


def test_coded_bucket_fetch_over_tcp(ctx):
    """The bucket_shard dcn protocol: framed shards served over
    tcp://, with the empty-payload miss sentinel for uncoded
    buckets."""
    from dpark_tpu.dcn import BucketServer
    from dpark_tpu.env import env
    from dpark_tpu.shuffle import read_bucket_shard
    coding.configure("rs(4,2)")
    LocalFileShuffle.write_buckets(779, 0, [[(3, 4)]])
    LocalFileShuffle.write_buckets(781, 0, [[(6, 7)]])
    srv = BucketServer(env.workdir, host="127.0.0.1").start()
    try:
        uri = "tcp://%s:%d" % srv.bind_address
        assert read_bucket_any(uri, 779, 0, 0) == [(3, 4)]
        # a shard request for an uncoded bucket = miss sentinel
        coding.configure(None)
        LocalFileShuffle.write_buckets(780, 0, [[(9, 1)]])
        with pytest.raises(FileNotFoundError):
            read_bucket_shard(uri, 780, 0, 0, 0)
        # ... and the coded fetch of it falls back to the plain path
        coding.configure("rs(4,2)")
        assert read_bucket_any(uri, 780, 0, 0) == [(9, 1)]
        # dedup satellite, coded flavor: duplicated tcp replicas of a
        # CODED bucket decode normally
        assert read_bucket_any([uri, uri], 781, 0, 0) == [(6, 7)]
    finally:
        srv.stop()


def test_reader_config_drift_decodes_with_writer_geometry(ctx):
    """The shard frames are SELF-DESCRIBING: a reader whose configured
    code drifted from the writer's (cross-host config skew, mid-run
    reconfigure) must decode with the WRITER's geometry, in both
    directions — never solve the wrong matrix against the payload
    bytes."""
    from dpark_tpu.dcn import BucketServer
    from dpark_tpu.env import env
    coding.configure("xor")                     # writer: n=5
    LocalFileShuffle.write_buckets(782, 0, [[(1, 2), (3, 4)]])
    coding.configure("rs(4,2)")                 # writer: n=6
    LocalFileShuffle.write_buckets(783, 0, [[(5, 6)]])
    srv = BucketServer(env.workdir, host="127.0.0.1").start()
    try:
        uri = "tcp://%s:%d" % srv.bind_address
        # reader rs(4,2) fans out 6 indices at an xor(4) bucket
        assert read_bucket_any(uri, 782, 0, 0) == [(1, 2), (3, 4)]
        # reader xor(4) fans out only 5 indices at an rs(4,2) bucket
        coding.configure("xor")
        assert read_bucket_any(uri, 783, 0, 0) == [(5, 6)]
    finally:
        srv.stop()


def test_job_record_decodes_baseline_is_per_job(ctx):
    """Decode counters are process-global; each job record reports
    only ITS OWN delta (and no decodes key at all with coding off)."""
    coding.configure("rs(4,2)")
    _reduce_job(ctx)
    first = ctx.scheduler.history[-1]["decodes"]
    _reduce_job(ctx)
    second = ctx.scheduler.history[-1]["decodes"]
    assert second["decode_failures"] == 0
    assert first["mode"] == second["mode"] == "rs(4,2)"
    coding.configure(None)
    _reduce_job(ctx)
    assert "decodes" not in ctx.scheduler.history[-1]


# ---------------------------------------------------------------------------
# plan lint: unbounded-recovery quiets under coding
# ---------------------------------------------------------------------------

def test_unbounded_recovery_quiet_when_coded(ctx):
    from dpark_tpu.analysis import lint_plan
    old = conf.LINT_WIDE_DEPTH
    conf.LINT_WIDE_DEPTH = 1
    try:
        r = ctx.parallelize([(i % 5, 1) for i in range(50)], 2) \
               .reduceByKey(operator.add, 2) \
               .map(lambda kv: (kv[1], kv[0])) \
               .reduceByKey(operator.add, 2)
        faults.configure("shuffle.fetch:p=0.1,seed=1")
        assert "unbounded-recovery" in {f.rule for f in lint_plan(r)}
        # coding with parity active: failed fetches decode, the chain
        # no longer needs a checkpoint pin under injection
        coding.configure("rs(4,2)")
        rules = {f.rule for f in lint_plan(r)}
        assert "unbounded-recovery" not in rules
        # plain wide-depth advice is unchanged by coding
        assert "plan-wide-depth" in rules
    finally:
        conf.LINT_WIDE_DEPTH = old


# ---------------------------------------------------------------------------
# HBM eviction round-trip (ISSUE 9 satellite): a coded bucket spilled
# to a disk shard container still decodes — including around a
# corrupted shard
# ---------------------------------------------------------------------------

def test_rs_bucket_decodes_after_eviction_roundtrip_to_disk():
    import glob
    import os

    from dpark_tpu import DparkContext
    from dpark_tpu.env import env
    coding.configure("rs(4,2)")
    ctx = DparkContext("tpu:2")
    ctx.start()
    try:
        r1 = ctx.parallelize([(i % 4, 1) for i in range(4000)], 2) \
                .reduceByKey(operator.add, 2)
        assert dict(r1.collect()) == {k: 1000 for k in range(4)}
        # budget pressure from a second job's exchange spills job 1's
        # completed HBM store into DISK shard containers
        old = conf.SHUFFLE_HBM_BUDGET
        conf.SHUFFLE_HBM_BUDGET = 1
        try:
            r2 = ctx.parallelize([(i % 3, 2) for i in range(900)], 2) \
                    .reduceByKey(operator.add, 2)
            assert dict(r2.collect()) == {k: 600 for k in range(3)}
        finally:
            conf.SHUFFLE_HBM_BUDGET = old
        shards = glob.glob(os.path.join(env.workdir, "shuffle",
                                        "*", "*", "*.shards"))
        assert shards, "eviction wrote no coded shard containers"
        # corrupt one DATA byte inside one container: the re-read must
        # decode around it from parity, not recompute the lineage
        victim = sorted(shards)[0]
        blob = bytearray(open(victim, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        with open(victim, "wb") as f:
            f.write(bytes(blob))
        coding.reset_counters()
        assert dict(r1.collect()) == {k: 1000 for k in range(4)}
        rec = ctx.scheduler.history[-1]
        assert rec.get("resubmits", 0) == 0, rec
        assert rec.get("recomputes", 0) == 0, rec
        stats = coding.stats()
        assert stats.get("repair", 0) > 0, stats
        assert stats.get("decode_failures", 0) == 0, stats
    finally:
        ctx.stop()


# ---------------------------------------------------------------------------
# straggler-adaptive per-exchange codes (ISSUE 19 tentpole 1)
# ---------------------------------------------------------------------------

def _heavy_tail_digest():
    from dpark_tpu.health import Sketch
    sk = Sketch()
    for _ in range(30):
        sk.add(0.005)
    for _ in range(5):
        sk.add(0.5)
    return sk.to_dict()


def _tight_tail_digest():
    from dpark_tpu.health import Sketch
    sk = Sketch()
    for _ in range(35):
        sk.add(0.005)
    return sk.to_dict()


@pytest.fixture()
def adaptive(tmp_path):
    """DPARK_CODE_ADAPT on, steering adapt plane with its own store,
    clean per-shuffle code registry."""
    from dpark_tpu import adapt
    old = conf.CODE_ADAPT
    conf.CODE_ADAPT = True
    adapt.configure(mode="on", store_dir=str(tmp_path / "adapt"))
    coding.clear_shuffle_codes()
    yield adapt
    conf.CODE_ADAPT = old
    coding.clear_shuffle_codes()
    adapt.configure()


def test_choose_code_policy_cells():
    """Pure-policy unit cells: no tails -> None (static stands);
    straggling tail or observed decode -> escalate; tight tails ->
    explicit uncoded; thin evidence -> None."""
    heavy, tight = _heavy_tail_digest(), _tight_tail_digest()
    spec, reason, _ = coding.choose_code([], {}, {})
    assert spec is None
    # straggling peer escalates to the conf'd spec
    spec, reason, pred = coding.choose_code(
        ["peerA"], {"peerA": heavy}, {"peerA": {"fetches": 10}})
    assert spec == conf.CODE_ADAPT_ESCALATE and "escalate" in reason
    assert pred and pred > 0
    # tight tails pin uncoded (drop the parity tax)
    spec, reason, _ = coding.choose_code(
        ["peerA"], {"peerA": tight}, {"peerA": {"fetches": 10}})
    assert spec == "off" and "tight" in reason
    # any observed decode escalates even with tight tails: the
    # exchange demonstrably consumed parity
    spec, reason, _ = coding.choose_code(
        ["peerA"], {"peerA": tight}, {"peerA": {"repair": 2}})
    assert spec == conf.CODE_ADAPT_ESCALATE and "decode" in reason
    # fewer samples than CODE_ADAPT_MIN_SAMPLES: not actionable
    from dpark_tpu.health import Sketch
    thin = Sketch()
    thin.add(0.5)
    spec, _, _ = coding.choose_code(["peerA"], {"peerA": thin.to_dict()},
                                    {})
    assert spec is None


def test_per_shuffle_code_registry_overrides_global():
    """The registry answers per shuffle id: explicit spec, explicit
    uncoded ("off" pins None even under a global code), fallback to
    the global code, and FIFO eviction at the cap."""
    coding.configure("rs(4,2)")
    coding.set_shuffle_code(101, "xor")
    coding.set_shuffle_code(102, "off")
    assert coding.shuffle_code(101).m == 1
    assert coding.shuffle_code(102) is None         # pinned uncoded
    assert coding.shuffle_code(999).m == 2          # global fallback
    coding.set_shuffle_code(101, None)              # clear
    assert coding.shuffle_code(101).m == 2
    coding.clear_shuffle_codes()
    assert coding.shuffle_code(102).m == 2


def test_two_run_escalation_targets_only_straggling_exchange(
        ctx, adaptive):
    """The ISSUE 19 two-run chaos proof: run 1 (static rs(4,2), fetch
    faults on exchange A only) records per-exchange decode outcomes;
    run 2 escalates exchange A (its xch record consumed parity) while
    exchange B — same peers, tight tails, clean history — is pinned
    UNCODED, dropping its parity tax under the same global code."""
    def job_a(c):
        return sorted(c.parallelize([(i % 7, i) for i in range(210)],
                                    4).reduceByKey(operator.add,
                                                   3).collect())

    def job_b(c):
        return sorted(c.parallelize([(i % 5, 1) for i in range(200)],
                                    4).reduceByKey(operator.add,
                                                   3).collect())

    coding.configure("rs(4,2)")
    clean_a, clean_b = job_a(ctx), job_b(ctx)
    # run 1: faults fire on A's exchange only; B runs clean
    faults.configure("shuffle.fetch:p=0.3,seed=7")
    assert job_a(ctx) == clean_a
    faults.configure(None)
    assert job_b(ctx) == clean_b
    from dpark_tpu import adapt
    xch = adapt.exchange_profiles()
    assert len(xch) >= 2, xch
    decoded = {site: sum(c.get("repair", 0) + c.get("straggler_win", 0)
                         for c in ent["peers"].values())
               for site, ent in xch.items()}
    assert any(v > 0 for v in decoded.values()), decoded
    assert any(v == 0 for v in decoded.values()), decoded
    # both exchanges share the local peer; its tails are tight — the
    # discriminator is A's recorded decode consumption
    adapt.record_site_tail("fetch.bucket:local", _tight_tail_digest())
    # run 2 under the same static code: A stays coded, B sheds parity
    p0 = coding.parity_bytes()
    assert job_a(ctx) == clean_a
    pa = coding.parity_bytes() - p0
    assert job_b(ctx) == clean_b
    pb = coding.parity_bytes() - p0 - pa
    assert pa > 0, "straggling exchange must stay coded"
    assert pb == 0, "clean tight-tailed exchange must shed parity"
    rec = ctx.scheduler.history[-1]
    ds = [d for d in (rec.get("adapt") or {}).get("decisions", ())
          if d.get("point") == "code"]
    assert ds and ds[0]["choice"] == "off" and ds[0]["applied"], ds
    hist = coding.code_history()
    assert any(h["code"] == conf.CODE_ADAPT_ESCALATE and h["applied"]
               for h in hist), hist
    assert any(h["code"] == "off" and h["applied"] for h in hist), hist


def test_heavy_tails_escalate_from_uncoded(ctx, adaptive):
    """With NO global code, an exchange whose recorded peer straggles
    (p99/p50 over the bar) escalates to parity on run 2 — and the
    pending decision closes with an observed fetch wall."""
    def job(c):
        return sorted(c.parallelize([(i % 7, i) for i in range(210)],
                                    4).reduceByKey(operator.add,
                                                   3).collect())

    from dpark_tpu import adapt
    clean = job(ctx)                       # run 1: records xch peers
    assert adapt.exchange_profiles(), "run 1 must persist xch record"
    adapt.record_site_tail("fetch.bucket:local", _heavy_tail_digest())
    p0 = coding.parity_bytes()
    assert job(ctx) == clean               # run 2: escalated
    assert coding.parity_bytes() > p0
    rec = ctx.scheduler.history[-1]
    ds = [d for d in (rec.get("adapt") or {}).get("decisions", ())
          if d.get("point") == "code"]
    assert ds and ds[0]["applied"], ds
    assert ds[0]["choice"] == conf.CODE_ADAPT_ESCALATE, ds
    assert ds[0].get("predicted_ms") is not None, ds
    assert ds[0].get("observed_ms") is not None, ds


def test_per_peer_decode_counters_and_metrics(ctx):
    """Satellite 1: decode counters carry the serving peer, and the
    /metrics render exposes dpark_decodes_by_peer_total plus the
    parity-bytes counter."""
    def job(c):
        return sorted(c.parallelize([(i % 7, i) for i in range(210)],
                                    4).reduceByKey(operator.add,
                                                   3).collect())

    from dpark_tpu import adapt
    adapt.configure(mode="observe")
    try:
        coding.configure("rs(4,2)")
        coding.reset_counters()
        faults.configure("shuffle.fetch:p=0.3,seed=7")
        job(ctx)
        stats = coding.stats()
        per_peer = stats["per_peer"]
        assert per_peer, stats
        assert any(c.get("repair", 0) > 0 for c in per_peer.values()), \
            per_peer
        assert stats["parity_bytes"] > 0, stats
        from dpark_tpu.web import render_metrics
        body = render_metrics(ctx.scheduler)
        assert "dpark_decodes_by_peer_total" in body
        assert 'peer="local"' in body, body
        assert "dpark_parity_bytes_total" in body
        assert "dpark_replans_total" in body
        # the plain decode metric never grows dict-valued series
        assert 'dpark_decodes_total{kind="per_peer"}' not in body
        # per-peer outcomes ride the health grade's evidence
        from dpark_tpu import health
        api = health.api_health(ctx.scheduler)
        assert api["subsystems"]["coding"]["evidence"].get("by_peer"), \
            api["subsystems"]["coding"]
    finally:
        adapt.configure()


def test_static_code_hint_tracks_recorded_tails(ctx, tmp_path):
    """The static-code-hint lint reads the adapt store's recorded
    fetch tails against the pinned code: parity over tight tails ->
    info (wasted parity), no parity over a straggling peer -> warn,
    and the rule goes quiet once DPARK_CODE_ADAPT supersedes the pin
    (ISSUE 19 satellite)."""
    from dpark_tpu import adapt
    from dpark_tpu.analysis import lint_plan

    def findings(r):
        return {f.rule: f for f in lint_plan(r)}

    r = ctx.parallelize([(i % 5, 1) for i in range(50)], 2) \
           .reduceByKey(operator.add, 2)
    old = conf.CODE_ADAPT
    conf.CODE_ADAPT = False
    try:
        # tight recorded tails + a pinned rs(4,2): the parity tax
        # buys nothing -> info
        adapt.configure(mode="on", store_dir=str(tmp_path / "tight"))
        adapt.record_site_tail("fetch.bucket:local",
                               _tight_tail_digest())
        coding.configure("rs(4,2)")
        f = findings(r).get("static-code-hint")
        assert f is not None and f.severity == "info", f
        assert "parity" in f.message

        # heavy recorded tails + no code pinned: recovery is lineage
        # replay -> warn naming the straggling peer
        adapt.configure(mode="on", store_dir=str(tmp_path / "heavy"))
        adapt.record_site_tail("fetch.bucket:slowpeer",
                               _heavy_tail_digest())
        coding.configure(None)
        f = findings(r).get("static-code-hint")
        assert f is not None and f.severity == "warn", f
        assert "slowpeer" in f.message

        # adaptive per-exchange pricing supersedes the pin: quiet
        conf.CODE_ADAPT = True
        assert "static-code-hint" not in findings(r)
        conf.CODE_ADAPT = False

        # adapt plane off: no recorded evidence to read -> quiet
        adapt.configure(mode="off")
        assert "static-code-hint" not in findings(r)
    finally:
        conf.CODE_ADAPT = old
        coding.configure(None)
        adapt.configure()
