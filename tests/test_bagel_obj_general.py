"""Generalized object-Bagel device adapter (VERDICT r4 #4): power-law
degrees beyond 8, messages to arbitrary targets (non-neighbors,
constants), pytree/vector vertex values, numeric edge values, and
variable message counts — all columnarize onto the device with parity
against the local object path."""

import operator
import random

import numpy as np
import pytest

from dpark_tpu.bagel import Bagel, BasicCombiner, Edge, Message, Vertex

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


def _run_both(program_fn, build_fn, max_superstep=80):
    from dpark_tpu import DparkContext
    outs = []
    used = False
    for master in ("tpu", "local"):
        c = DparkContext(master)
        c.start()
        try:
            verts, msgs, combiner = build_fn(c)
            final = Bagel.run(c, verts, msgs, program_fn,
                              combiner=combiner,
                              max_superstep=max_superstep)
            outs.append({vid: v.value for vid, v in final.collect()})
            if master == "tpu":
                used = getattr(c.scheduler, "_pregel_device_used",
                               False)
        finally:
            c.stop()
    return outs[0], outs[1], used


def _close(a, b, tol=1e-9):
    assert set(a) == set(b)
    for k in a:
        va, vb = a[k], b[k]
        la = va if isinstance(va, (tuple, list, np.ndarray)) else [va]
        lb = vb if isinstance(vb, (tuple, list, np.ndarray)) else [vb]
        assert np.allclose(np.asarray(la, np.float64),
                           np.asarray(lb, np.float64),
                           rtol=tol, atol=tol), (k, va, vb)


def _power_law_graph(n=400, seed=7):
    """Degrees drawn from a power-law-ish ladder with max degree 128
    (>> the old cap of 8) while keeping distinct classes within the
    trace budget."""
    ladder = [0, 1, 1, 1, 2, 2, 3, 4, 5, 6, 8, 10, 13, 16,
              20, 26, 32, 40, 64, 128]
    rng = random.Random(seed)
    degs = [ladder[min(int(rng.paretovariate(1.1)) - 1, len(ladder) - 1)]
            for _ in range(n)]
    degs[0] = 128                      # guarantee the heavy hub exists
    verts = []
    for i in range(n):
        edges = [Edge(rng.randrange(n)) for _ in range(degs[i])]
        verts.append((i, Vertex(i, 1.0 / n, edges)))
    return verts


def test_power_law_pagerank_rides_device():
    """PageRank on a power-law graph: max degree 128, ~15 degree
    classes — columnarizes (the r4 adapter refused anything past
    degree 8) and matches the local object loop."""
    n = 400
    verts_rows = _power_law_graph(n)
    assert max(len(v.outEdges) for _, v in verts_rows) == 128

    def compute(vert, msg, agg, s):
        new = vert.value if s == 0 else (
            0.15 / n + 0.85 * (msg if msg is not None else 0.0))
        v = Vertex(vert.id, new, vert.outEdges, s < 8)
        if s < 8 and vert.outEdges:
            share = new / len(vert.outEdges)
            return (v, [Message(e.target_id, share)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        return (c.parallelize(verts_rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used, "power-law program did not ride the device"
    _close(tpu, local)


def test_messages_to_non_neighbors():
    """Targets are COMPUTED ids, not edges at all — the r4 adapter's
    own-out-edges-only rule is gone; delivery is a hash(dst)
    exchange."""
    n = 64

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 3)
        if s < 3:
            # send to a hashed non-neighbor (the graph has NO edges)
            return (v, [Message((vert.id * vert.id + 7) % n,
                                vert.id + 1)])
        return (v, [])

    def build(c):
        rows = [(i, Vertex(i, 0, [])) for i in range(n)]
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used, "computed-target program did not ride the device"
    assert tpu == local


def test_message_to_constant_hub():
    """A constant Python-int target (everyone notifies vertex 0)."""
    n = 40

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 2)
        if s < 2:
            return (v, [Message(0, 1)])
        return (v, [])

    def build(c):
        rows = [(i, Vertex(i, 0, [])) for i in range(n)]
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used
    assert tpu == local
    assert local[0] == 2 * n             # hub got everyone's 1, twice


def test_variable_message_count():
    """Emitting ONE message despite many out-edges (notify-first) —
    the r4 adapter required exactly one message per out-edge."""
    n = 48
    rng = random.Random(3)
    rows = [(i, Vertex(i, 0,
                       [Edge(rng.randrange(n)) for _ in range(6)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 3)
        if s < 3 and vert.outEdges:
            return (v, [Message(vert.outEdges[0].target_id, 1)])
        return (v, [])

    def build(c):
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used
    assert tpu == local


def test_tuple_vertex_values():
    """Vertex.value as a (count, weight) tuple — pytree leaves ride as
    separate device columns."""
    n = 32
    rows = [(i, Vertex(i, (0, float(i)), [Edge((i + 1) % n)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        cnt, w = vert.value
        got = msg if msg is not None else 0.0
        v = Vertex(vert.id, (cnt + 1, w + got), vert.outEdges, s < 4)
        if s < 4:
            return (v, [Message(e.target_id, w * 0.5)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used, "tuple-valued program did not ride the device"
    _close(tpu, local)


def test_edge_values_ride_device():
    """Numeric Edge.value feeds the emitted messages (weighted
    propagation)."""
    n = 32
    rng = random.Random(11)
    rows = [(i, Vertex(i, 1.0,
                       [Edge((i + k) % n, rng.random())
                        for k in (1, 2, 3)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0.0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 3)
        if s < 3:
            return (v, [Message(e.target_id, vert.value * e.value)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used, "edge-valued program did not ride the device"
    _close(tpu, local)


def test_too_many_degree_classes_now_bucketizes():
    """More distinct degrees than the exact-class trace budget used to
    force the host path; power-of-two degree buckets (ISSUE 4) fold
    the 40 distinct degrees into <= 11 classes — the graph
    COLUMNARIZES with parity.  With DPARK_BAGEL_BUCKETS off the old
    fallback behavior (host path, parity intact) is preserved."""
    from dpark_tpu import bagel as bagel_mod
    from dpark_tpu.backend.tpu import bagel_obj
    n = 80
    rows = [(i, Vertex(i, 0, [Edge((i + k) % n)
                              for k in range(1, 2 + (i % 40))]))
            for i in range(n)]
    assert len({len(v.outEdges) for _, v in rows}) \
        > bagel_mod.MAX_DEGREE_CLASSES

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 2)
        if s < 2:
            return (v, [Message(e.target_id, 1)
                        for e in vert.outEdges])
        return (v, [])

    def build(c):
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used, "degree buckets should columnarize >24 classes"
    stats = dict(bagel_obj.LAST_RUN_STATS)
    assert stats["bucketed"], stats
    assert stats["classes"] <= 11, stats
    assert stats["distinct_degrees"] > bagel_mod.MAX_DEGREE_CLASSES, \
        stats
    assert tpu == local

    old = bagel_mod.DEGREE_BUCKETS
    bagel_mod.DEGREE_BUCKETS = False
    try:
        tpu2, local2, used2 = _run_both(compute, build)
    finally:
        bagel_mod.DEGREE_BUCKETS = old
    assert not used2
    assert tpu2 == local2


def test_non_integer_target_falls_back():
    """A string message target is outside the columnar subset but must
    still run correctly on the host path."""
    rows = [("a", Vertex("a", 0, [])), ("b", Vertex("b", 0, []))]

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 2)
        if s < 2:
            return (v, [Message("a", 1)])
        return (v, [])

    def build(c):
        return (c.parallelize(rows, 2), c.parallelize([], 2),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert not used
    assert tpu == local
    assert local["a"] == 4               # both vertices notify "a" twice


def test_degree_dependent_compute_uses_exact_classes():
    """A compute that consults len(outEdges) (pagerank's share split)
    is UNSOUND under padded buckets: the adapter detects it (len
    recording + the exact-vs-bucket canary) and falls back to exact
    degree classes — still on device, parity intact."""
    from dpark_tpu.backend.tpu import bagel_obj
    n = 60
    rows = [(i, Vertex(i, 1.0, [Edge((i + k + 1) % n)
                                for k in range(1 + i % 5)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        v = vert.value + (msg if msg is not None else 0.0)
        out = []
        if s < 2:
            share = v / len(vert.outEdges)
            out = [Message(e.target_id, share) for e in vert.outEdges]
        return Vertex(vert.id, v, vert.outEdges, s < 2), out

    def build(c):
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(operator.add))

    tpu, local, used = _run_both(compute, build)
    assert used
    stats = dict(bagel_obj.LAST_RUN_STATS)
    assert not stats["bucketed"], stats       # exact classes took over
    _close(tpu, local)


def test_vector_message_values_ride_device():
    """Message.value as a (count, sum-vector) pytree (ISSUE 4
    satellite): leaves ride as extra exchange columns and the user's
    pairwise op traces as a structure-preserving merge over the leaf
    tuple — parity vs the local object loop."""
    from dpark_tpu.backend.tpu import bagel_obj
    n = 36
    rows = [(i, Vertex(i, (0.0, np.zeros(3)),
                       [Edge((i + k + 1) % n)
                        for k in range(1 + i % 3)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        cnt, vec = vert.value
        if msg is not None:
            mc, mv = msg
            cnt = cnt + mc
            vec = vec + mv
        out = []
        if s < 3:
            out = [Message(e.target_id,
                           (1.0, np.ones(3) * (s + 1.0)))
                   for e in vert.outEdges]
        return Vertex(vert.id, (cnt, vec), vert.outEdges, s < 3), out

    def build(c):
        return (c.parallelize(rows, 8), c.parallelize([], 8),
                BasicCombiner(lambda a, b: (a[0] + b[0], a[1] + b[1])))

    tpu, local, used = _run_both(compute, build)
    assert used
    stats = dict(bagel_obj.LAST_RUN_STATS)
    assert stats["msg_leaves"] == 2 and stats["msg_merge"] == "traced", \
        stats
    assert set(tpu) == set(local)
    for k in tpu:
        assert np.isclose(float(tpu[k][0]), float(local[k][0])), k
        assert np.allclose(np.asarray(tpu[k][1], np.float64),
                           np.asarray(local[k][1], np.float64)), k


def test_vector_message_single_leaf_monoid():
    """A single ndarray message leaf with a classified op (np.add)
    combines through the per-leaf monoid — no traced merge needed."""
    from dpark_tpu.backend.tpu import bagel_obj
    n = 24
    rows = [(i, Vertex(i, np.zeros(2),
                       [Edge((i + 1) % n), Edge((i + 2) % n)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        v = vert.value + (msg if msg is not None else np.zeros(2))
        out = []
        if s < 2:
            out = [Message(e.target_id, np.ones(2) * (s + 1.0))
                   for e in vert.outEdges]
        return Vertex(vert.id, v, vert.outEdges, s < 2), out

    def build(c):
        return (c.parallelize(rows, 4), c.parallelize([], 4),
                BasicCombiner(np.add))

    tpu, local, used = _run_both(compute, build)
    assert used
    stats = dict(bagel_obj.LAST_RUN_STATS)
    assert stats["msg_leaves"] == 1 and stats["msg_merge"] == "monoid", \
        stats
    _close(tpu, local)


def test_bagel_compile_budget_guard_falls_back():
    """With DPARK_BAGEL_MIN_ROWS_PER_TRACE far above the graph size,
    the adapter refuses to spend compiles and the host loop answers —
    parity intact."""
    from dpark_tpu import bagel as bagel_mod
    n = 24
    rows = [(i, Vertex(i, 0, [Edge((i + 1 + k) % n)
                              for k in range(1 + i % 3)]))
            for i in range(n)]

    def compute(vert, msg, agg, s):
        got = msg if msg is not None else 0
        v = Vertex(vert.id, vert.value + got, vert.outEdges, s < 2)
        return (v, [Message(e.target_id, 1)
                    for e in vert.outEdges] if s < 2 else [])

    def build(c):
        return (c.parallelize(rows, 4), c.parallelize([], 4),
                BasicCombiner(operator.add))

    old = bagel_mod.BAGEL_MIN_ROWS_PER_TRACE
    bagel_mod.BAGEL_MIN_ROWS_PER_TRACE = 10_000_000
    try:
        tpu, local, used = _run_both(compute, build)
    finally:
        bagel_mod.BAGEL_MIN_ROWS_PER_TRACE = old
    assert not used
    assert tpu == local
