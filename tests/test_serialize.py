"""Closure shipping round-trips (reference test: tests/test_serialize.py)."""

import functools
import pickle

from dpark_tpu.serialize import dumps, loads


def test_plain_lambda():
    f = loads(dumps(lambda x: x + 1))
    assert f(1) == 2


def test_closure_capture():
    n = 10

    def add_n(x):
        return x + n
    g = loads(dumps(add_n))
    assert g(5) == 15


def test_nested_closures():
    def outer(a):
        def inner(b):
            return a * b
        return inner
    f = loads(dumps(outer(3)))
    assert f(4) == 12


def test_recursive_function():
    def fact(n):
        return 1 if n <= 1 else n * fact(n - 1)
    g = loads(dumps(fact))
    assert g(5) == 120


def test_mutual_recursion_via_globals():
    assert loads(dumps(_is_even))(10) is True
    assert loads(dumps(_is_even))(7) is False


def _is_even(n):
    return True if n == 0 else _is_odd(n - 1)


def _is_odd(n):
    return False if n == 0 else _is_even(n - 1)


def test_defaults_and_kwargs():
    def f(a, b=2, *, c=3):
        return a + b + c
    g = loads(dumps(f))
    assert g(1) == 6
    assert g(1, 10, c=100) == 111


def test_partial():
    f = functools.partial(_mul, 3)
    assert loads(dumps(f))(7) == 21


def _mul(a, b):
    return a * b


def test_module_function_by_reference():
    g = loads(dumps(pickle.dumps))
    assert g is pickle.dumps


def test_bound_method_of_local_instance():
    class Adder:
        def __init__(self, n):
            self.n = n

        def add(self, x):
            return self.n + x
    # class defined in a local scope -> method must ship by value
    a = Adder(4)
    try:
        g = loads(dumps(a.add))
        assert g(3) == 7
    except (pickle.PicklingError, AttributeError):
        # local classes by value are best-effort (documented limitation)
        pass


def test_generator_function():
    def gen(n):
        for i in range(n):
            yield i * i
    g = loads(dumps(gen))
    assert list(g(4)) == [0, 1, 4, 9]


def test_lambda_capturing_module_global():
    g = loads(dumps(lambda x: _mul(x, 5)))
    assert g(2) == 10
