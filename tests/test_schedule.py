"""Scheduler-level regression tests: job isolation, retries, lineage
recovery (reference: FetchFailed -> parent resubmit, SURVEY.md 5.3)."""

import os

import pytest


def test_abandoned_job_does_not_poison_next(ctx):
    r = ctx.parallelize(range(100), 10)
    # take() abandons its run_job generator after the first partitions
    assert r.take(25) == list(range(25))
    # any later job must be unaffected by stale completions
    assert r.count() == 100
    assert r.collect() == list(range(100))
    it = r.iterate()
    next(it)
    del it                       # abandon mid-iteration
    assert r.sum() == 4950


def test_sortbykey_single_output_partition(ctx):
    r = ctx.parallelize([(3, "a"), (1, "b"), (2, "c"), (0, "d")], 2)
    got = r.sortByKey(numSplits=1).collect()
    assert [k for k, _ in got] == [0, 1, 2, 3]


def test_pipe_abandoned_and_failing(ctx):
    r = ctx.parallelize([str(i) for i in range(1000)], 1)
    assert r.pipe("cat").take(1) == ["0"]
    bad = ctx.parallelize(["x"], 1).pipe("false")
    with pytest.raises(RuntimeError):
        bad.collect()


def test_task_retry_then_abort(ctx):
    # deterministic failure aborts after MAX_TASK_FAILURES
    r = ctx.parallelize([0], 1).map(lambda x: 1 // x)
    with pytest.raises(RuntimeError) as e:
        r.collect()
    assert "failed" in str(e.value)


def test_lineage_recovery_fetch_failed(ctx):
    """Delete a map output file after the map stage completes; the reduce
    must trigger parent-stage recomputation, not fail the job."""
    from dpark_tpu.env import env
    r = ctx.parallelize([(i % 4, 1) for i in range(100)], 4) \
           .reduceByKey(lambda a, b: a + b, 2)
    assert dict(r.collect()) == {0: 25, 1: 25, 2: 25, 3: 25}
    # simulate lost map outputs: blow away the shuffle dir, then rerun a
    # NEW shuffle downstream of the same cached tracker state
    shuffle_dir = os.path.join(env.workdir, "shuffle")
    for root, _, files in os.walk(shuffle_dir):
        for f in files:
            os.unlink(os.path.join(root, f))
    # new job on the same rdd graph: reduce tasks fetch, hit FetchFailed,
    # scheduler resubmits the parent map stage
    assert dict(r.collect()) == {0: 25, 1: 25, 2: 25, 3: 25}


def test_fetch_failed_partial_invalidation(ctx):
    """Losing ONE map output must not invalidate the healthy ones in the
    map-output tracker (round-1 advisor fix): the fetch_failed handler
    registers the surviving locations with only the lost entry nulled."""
    from dpark_tpu.env import env
    calls = []
    orig = env.map_output_tracker.register_outputs

    def spy(sid, locs):
        calls.append(list(locs))
        return orig(sid, locs)

    env.map_output_tracker.register_outputs = spy
    try:
        r = ctx.parallelize([(i % 4, 1) for i in range(100)], 4) \
               .reduceByKey(lambda a, b: a + b, 2)
        assert dict(r.collect()) == {0: 25, 1: 25, 2: 25, 3: 25}
        victim = None
        for root, _, files in os.walk(os.path.join(env.workdir,
                                                   "shuffle")):
            for f in sorted(files):
                victim = os.path.join(root, f)
                break
            if victim:
                break
        os.unlink(victim)
        assert dict(r.collect()) == {0: 25, 1: 25, 2: 25, 3: 25}
    finally:
        env.map_output_tracker.register_outputs = orig
    # the invalidation registration (the one with holes) must keep the
    # healthy outputs: exactly one None, never [None]*n
    partial = [locs for locs in calls if any(l is None for l in locs)]
    assert partial, "fetch_failed never re-registered the parent outputs"
    for locs in partial:
        assert sum(1 for l in locs if l is None) == 1


def test_save_by_key_overwrite_and_atomic(ctx, tmp_path):
    """saveAsTextFileByKey honors overwrite=False, replaces atomically on
    overwrite=True, and leaves no tmp litter (round-1 advisor fix)."""
    out = str(tmp_path / "bykey")
    ctx.parallelize([("a", "v1")], 1).saveAsTextFileByKey(out)
    part = os.path.join(out, "a", "part-00000")
    assert open(part).read() == "v1\n"
    ctx.parallelize([("a", "v2")], 1).saveAsTextFileByKey(out)
    assert open(part).read() == "v2\n"            # overwrite default
    ctx.parallelize([("a", "v3")], 1) \
       .saveAsTextFileByKey(out, overwrite=False)
    assert open(part).read() == "v2\n"            # kept
    for root, _, files in os.walk(out):
        for f in files:
            assert not f.startswith(".tmp-"), "tmp litter: %s" % f


def test_sort_shuffle_conf(ctx):
    from dpark_tpu import conf
    old = conf.SORT_SHUFFLE
    conf.SORT_SHUFFLE = True
    try:
        got = dict(ctx.parallelize([(i % 5, i) for i in range(100)], 4)
                   .reduceByKey(lambda a, b: a + b, 3).collect())
        expect = {}
        for i in range(100):
            expect[i % 5] = expect.get(i % 5, 0) + i
        assert got == expect
    finally:
        conf.SORT_SHUFFLE = old


def test_save_as_text_file_by_key(ctx, tmp_path):
    data = [("a", "line1"), ("b", "line2"), ("a", "line3")]
    ctx.parallelize(data, 2).saveAsTextFileByKey(str(tmp_path / "bykey"))
    a_lines = []
    for root, _, files in os.walk(str(tmp_path / "bykey" / "a")):
        for f in files:
            a_lines.extend(open(os.path.join(root, f)).read().split())
    assert sorted(a_lines) == ["line1", "line3"]


def test_disk_spill_merger(ctx):
    """Force tiny spill threshold; result must still be exact."""
    from dpark_tpu import conf
    from dpark_tpu.shuffle import DiskSpillMerger
    from dpark_tpu.dependency import Aggregator
    agg = Aggregator(lambda v: v, lambda a, b: a + b, lambda a, b: a + b)
    m = DiskSpillMerger(agg, max_items=10)
    for batch in range(20):
        m.merge([(k, 1) for k in range(25)])
    got = dict(m)
    assert got == {k: 20 for k in range(25)}


def test_speculative_relaunch(pctx):
    """One straggler among fast tasks triggers a speculative duplicate;
    results stay correct and the duplicate is recorded."""
    from dpark_tpu import conf

    def slow_partition(i, it):
        import time as _t
        items = list(it)
        if i == 0:
            _t.sleep(4)
        return [sum(items)]

    old = (conf.SPECULATION_MULTIPLIER, conf.SPECULATION_QUANTILE)
    conf.SPECULATION_MULTIPLIER = 1.5
    conf.SPECULATION_QUANTILE = 0.5
    try:
        r = pctx.parallelize(list(range(100)), 10) \
                .mapPartitionsWithIndex(slow_partition)
        got = r.collect()
        assert sum(got) == 4950
        assert pctx.scheduler.history[-1].get("speculated", 0) >= 1
    finally:
        conf.SPECULATION_MULTIPLIER, conf.SPECULATION_QUANTILE = old


def test_worker_crash_recovers(pctx, tmp_path):
    """A worker process dying mid-task (reference: executor lost) breaks
    the pool visibly; the pool restarts and retries complete the job."""
    marker = str(tmp_path / "crashed_once")

    def volatile(i, it):
        import os as _os
        items = list(it)
        if i == 0 and not _os.path.exists(marker):
            open(marker, "w").close()
            _os._exit(1)               # simulate OOM-kill / segfault
        return [sum(items)]

    got = pctx.parallelize(list(range(40)), 4) \
              .mapPartitionsWithIndex(volatile).collect()
    assert sum(got) == sum(range(40))
    assert os.path.exists(marker)


def _task_hosts(sched):
    """{partition: host} from the LAST job's per-task records."""
    rec = sched.history[-1]
    out = {}
    for st in rec["stage_info"]:
        for t in st.get("tasks", ()):
            out[t["p"]] = t["host"]
    return out


def test_fleet_chunkserver_hint_places_task_on_holder(tmp_path):
    """Locality earns a test (ISSUE 3 satellite): two workdir-distinct
    inline executors on one host; a chunkserver location hint names one
    of them, and the per-task host records in schedule.py show the task
    ran THERE — not wherever round-robin would have sent it."""
    from dpark_tpu import DparkContext
    from dpark_tpu.file_manager.chunkserver import ChunkServer

    root = tmp_path / "dfs"
    root.mkdir()
    with open(root / "a.txt", "w") as f:
        f.write("alpha beta\n" * 200)
    # every chunk of every file is held by executor exec-1
    srv = ChunkServer(str(root),
                      host_map=lambda path, idx: ["exec-1"]).start()
    try:
        ctx = DparkContext("fleet:2")
        ctx.start()
        sched = ctx.scheduler
        assert [e.host for e in sched.executors] == ["exec-0", "exec-1"]
        assert sched.executors[0].workdir != sched.executors[1].workdir
        r = ctx.textFile("cfs://%s/a.txt" % srv.addr)
        sp = r.splits[0]
        assert r.preferred_locations(sp) == ["exec-1"]
        total = r.map(lambda line: len(line.split())).sum()
        assert total == 400
        # EVERY map task over the served file ran on the holder
        rec = sched.history[-1]
        hosts = [t["host"] for st in rec["stage_info"]
                 for t in st.get("tasks", ())]
        assert hosts and set(hosts) == {"exec-1"}, hosts
        ctx.stop()
    finally:
        srv.stop()


def test_fleet_cached_partition_hint_places_followup_job():
    """A cached RDD records which executor computed each partition; the
    NEXT job over it runs its tasks at the holders (asserted via the
    per-task host records), while an uncached job round-robins."""
    from dpark_tpu import DparkContext

    ctx = DparkContext("fleet:2")
    ctx.start()
    sched = ctx.scheduler
    r = ctx.parallelize(range(100), 4).map(lambda x: x * 2).cache()
    assert sum(r.collect()) == 9900
    first = _task_hosts(sched)
    assert set(first.values()) == {"exec-0", "exec-1"}  # round-robin
    assert sched.cache_locs          # holders recorded at cache time
    # second job: every task placed on its partition's recorded holder
    assert r.count() == 100
    second = _task_hosts(sched)
    for p, host in second.items():
        assert host == sched.cache_locs[(r.id, p)], (p, second)
    ctx.stop()
