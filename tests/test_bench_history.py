"""Bench trajectory tooling (ISSUE 15 satellite): tools/bench_history
extracts a run's headline ratios, appends them to the trajectory
file, and diffs vs the previous entry."""

import json
import os

import pytest


def _load():
    from tests.conftest import load_tool
    return load_tool("bench_history.py")


FAKE_RUN_1 = """
# noise the extractor must skip
{"metric": "reduceByKey_GBps_per_chip_EMULATED_CPU", "value": 0.02, "vs_baseline": 4.1}
{"metric": "table_query_device_vs_host", "value": 9.2}
{"metric": "bulk_channel_vs_bridge", "value": 16.6}
{"metric": "adapt_warm_vs_cold", "value": 0.18}
{"metric": "service_warm_submit", "value": 4.7}
{"metric": "health_plane_overhead", "value": 0.97}
{"metric": "ledger_plane_overhead", "value": 1.01}
{"metric": "unrelated_metric", "value": 123.0}
not json at all
"""

FAKE_RUN_2 = """
{"metric": "reduceByKey_GBps_per_chip_EMULATED_CPU", "value": 0.02, "vs_baseline": 3.9}
{"metric": "table_query_device_vs_host", "value": 4.0}
{"metric": "bulk_channel_vs_bridge", "value": 17.0}
{"metric": "health_plane_overhead", "value": 1.10}
{"metric": "ledger_plane_overhead", "value": 1.0}
"""


def test_extract_ratios():
    bh = _load()
    ratios = bh.extract_ratios(FAKE_RUN_1.splitlines())
    assert ratios == {"reduce_vs_baseline": 4.1,
                      "table_device_vs_host": 9.2,
                      "bulk_channel_vs_bridge": 16.6,
                      "adapt_warm_vs_cold": 0.18,
                      "service_warm_submit": 4.7,
                      "health_plane_overhead": 0.97,
                      "ledger_plane_overhead": 1.01}


def test_append_and_diff(tmp_path, capsys):
    bh = _load()
    out = str(tmp_path / "BENCH_TRAJECTORY.jsonl")
    run1 = tmp_path / "run1.txt"
    run1.write_text(FAKE_RUN_1)
    assert bh.main([str(run1), "--out", out, "--label", "t1"]) == 0
    text = capsys.readouterr().out
    assert "trajectory was empty" in text
    entries = bh.load_trajectory(out)
    assert len(entries) == 1
    assert entries[0]["seq"] == 1 and entries[0]["label"] == "t1"

    run2 = tmp_path / "run2.txt"
    run2.write_text(FAKE_RUN_2)
    assert bh.main([str(run2), "--out", out]) == 0
    text = capsys.readouterr().out
    # the diff names the slide: table ratio halved (regressed), bulk
    # improved, health overhead rose (regressed on a lower-is-better)
    assert "table_device_vs_host" in text
    assert "regressed" in text
    entries = bh.load_trajectory(out)
    assert len(entries) == 2 and entries[1]["seq"] == 2
    # metrics missing from run 2 (service/adapt) simply don't diff
    assert "adapt_warm_vs_cold" not in entries[1]["ratios"]


def test_gate_fails_on_regression(tmp_path, capsys):
    bh = _load()
    out = str(tmp_path / "traj.jsonl")
    run1 = tmp_path / "run1.txt"
    run1.write_text(FAKE_RUN_1)
    run2 = tmp_path / "run2.txt"
    run2.write_text(FAKE_RUN_2)
    assert bh.main([str(run1), "--out", out]) == 0
    # table_device_vs_host dropped 9.2 -> 4.0 (-57%): gate at 20%
    assert bh.main([str(run2), "--out", out, "--gate", "20"]) == 1
    assert "FAIL" in capsys.readouterr().out
    # without the gate the same diff is informational
    assert bh.main([str(run2), "--out", out]) == 0


def test_empty_input_fails(tmp_path):
    bh = _load()
    empty = tmp_path / "empty.txt"
    empty.write_text("no metrics here\n")
    assert bh.main([str(empty), "--out",
                    str(tmp_path / "t.jsonl")]) == 1


def test_corrupt_trajectory_lines_skip(tmp_path):
    bh = _load()
    out = tmp_path / "traj.jsonl"
    out.write_text('{"seq": 1, "ratios": {"bulk_channel_vs_bridge": '
                   '2.0}}\nGARBAGE LINE\n')
    run1 = tmp_path / "run1.txt"
    run1.write_text(FAKE_RUN_1)
    assert bh.main([str(run1), "--out", str(out)]) == 0
    entries = bh.load_trajectory(str(out))
    assert len(entries) == 2
    assert entries[-1]["seq"] == 2
