"""Query parity fuzzer (ISSUE 13 satellite): random select / filter /
group-by / join trees over seeded int/float/string columns must be
BIT-IDENTICAL between the device query plan (DPARK_QUERY on) and the
host object path (DPARK_QUERY off, the pre-plan row path) — on the
local master and on a 2-device tpu mesh, over both in-memory and
tabular-file sources.  Float columns are seeded integer-valued so
device f64 folds are exact (the documented GROUP_AGG_REWRITE-style
float caveat is about reassociation, not correctness).

Plus one chaos cell: a grouped query over a coded shuffle under
injected fetch faults completes with resubmits == recomputes == 0 —
erasure decode, not lineage replay, absorbs the failures."""

import os
import random

import pytest


def make_rows(rng, n):
    return [(rng.randint(0, 12),
             rng.randint(-40, 40),
             float(rng.randint(-30, 30)),
             "w%d" % rng.randint(0, 6))
            for _ in range(n)]


FIELDS = "k a f s"

WHERES = [
    "a > {c}", "a <= {c}", "a % {m} == {r}", "f >= {c}",
    "s == 'w{j}'", "a > {c} and s == 'w{j}'",
    "not (a % {m} == {r})", "k + a < {c}",
]

AGG_POOL = ["sum(a) as sa", "count(*) as c", "avg(f) as af",
            "min(a) as mn", "max(f) as mx", "avg(a) as aa",
            "sum(a * 2 + f) as sx", "max(a) as ma"]


def build_query(rng):
    """A random DSL program as a list of (op, params), applied
    identically on both sides."""
    prog = []
    if rng.random() < 0.7:
        w = rng.choice(WHERES).format(
            c=rng.randint(-20, 20), m=rng.randint(2, 5),
            r=rng.randint(0, 1), j=rng.randint(0, 6))
        prog.append(("where", w))
    if rng.random() < 0.3:
        prog.append(("select",
                     ["k", "a * %d + 1 as a" % rng.randint(1, 3),
                      "f", "s"]))
    shape = rng.choice(["group", "group", "join", "join_group",
                        "scan"])
    if shape in ("join", "join_group"):
        on = rng.choice(["k", "s"])
        prog.append(("join", on, rng.randint(0, 2 ** 30)))
    if shape in ("group", "join_group"):
        keys = rng.choice([["k"], ["s"], ["k", "s"], ["k % 3"]])
        if shape == "join_group":
            keys = rng.choice([["k"], ["s"], ["dv"]])
        aggs = rng.sample(AGG_POOL, rng.randint(1, 3))
        if shape == "join_group":
            # joined-group keys/args must be plain joined columns
            aggs = rng.sample(["sum(a) as sa", "count(*) as c",
                               "min(a) as mn", "avg(f) as af"],
                              rng.randint(1, 2))
        prog.append(("group", keys, aggs))
    if rng.random() < 0.4:
        prog.append(("sort",))
    return prog


def apply_query(ctx, table, prog):
    t = table
    for step in prog:
        op = step[0]
        if op == "where":
            t = t.where(step[1])
        elif op == "select":
            t = t.select(*step[1])
        elif op == "join":
            _, on, seed2 = step
            r2 = random.Random(seed2)
            if on == "k":
                dim = [(i, r2.randint(0, 99)) for i in range(13)]
            else:
                dim = [("w%d" % i, r2.randint(0, 99))
                       for i in range(7)]
            dt = ctx.parallelize(dim, 2).asTable([on, "dv"], "dim")
            t = t.join(dt, on=on)
        elif op == "group":
            t = t.groupBy(step[1], *step[2])
        elif op == "sort":
            t = t.sort(t.fields[0])
    return t


def canonical(rows):
    return sorted(tuple(r) for r in rows)


def _run_cell(master, seed, source):
    from dpark_tpu import DparkContext, conf
    rng = random.Random(seed)
    rows = make_rows(rng, rng.choice([200, 1500]))
    prog = build_query(rng)
    ctx = DparkContext(master)
    lctx = DparkContext("local")
    tmpdir = None
    try:
        ctx.start()
        lctx.start()

        def table_for(c):
            if source == "tabular":
                return c.tabular(tmpdir).asTable("t")
            return c.parallelize(rows, 4).asTable(FIELDS, "t")

        if source == "tabular":
            import tempfile
            from dpark_tpu.tabular import write_tabular
            tmpdir = tempfile.mkdtemp()
            write_tabular(os.path.join(tmpdir, "part-00000.tab"),
                          FIELDS.split(), rows, chunk_rows=256)
        conf.QUERY_PLAN = True
        dev = apply_query(ctx, table_for(ctx), prog)
        got = canonical(dev.collect())
        got_n = dev.count()
        conf.QUERY_PLAN = False
        try:
            host = apply_query(lctx, table_for(lctx), prog)
            expect = canonical(host.collect())
            expect_n = host.count()
        finally:
            conf.QUERY_PLAN = True
        assert got == expect, \
            "parity violation for %r (seed %d): %r vs %r" \
            % (prog, seed, got[:3], expect[:3])
        assert got_n == expect_n == len(expect), (prog, seed)
    finally:
        ctx.stop()
        lctx.stop()


@pytest.mark.parametrize("seed", range(12))
def test_query_parity_local(seed):
    _run_cell("local", seed,
              "tabular" if seed % 3 == 0 else "memory")


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.mesh
def test_query_parity_tpu2(seed):
    _run_cell("tpu:2", 100 + seed,
              "tabular" if seed % 2 == 0 else "memory")


def test_query_chaos_coded_shuffle():
    """Chaos cell: a grouped query over a coded shuffle under
    shuffle.fetch:p=0.2 — bit-identical to the clean run with ZERO
    resubmits/recomputes (decode absorbs every injected failure)."""
    from dpark_tpu import DparkContext, coding, conf, faults
    rows = make_rows(random.Random(77), 3000)
    ctx = DparkContext("local")
    ctx.start()
    try:
        def q():
            t = ctx.parallelize(rows, 4).asTable(FIELDS, "t")
            return canonical(
                t.where("a > -10")
                 .groupBy("k", "sum(a) as sa", "count(*) as c",
                          "avg(f) as af").collect())
        conf.QUERY_PLAN = True
        clean = q()
        coding.configure("rs(4,2)")
        faults.configure("shuffle.fetch:p=0.2,seed=7")
        try:
            chaotic = q()
            fired = faults.stats()["shuffle.fetch"]["fired"]
            rec = ctx.scheduler.history[-1]
        finally:
            faults.configure(None)
            coding.configure(None)
        assert chaotic == clean
        assert fired > 0, "injection never fired"
        assert rec.get("resubmits", 0) == 0, rec
        assert rec.get("recomputes", 0) == 0, rec
        assert rec["decodes"]["repair"] > 0, rec.get("decodes")
        assert rec["decodes"]["decode_failures"] == 0
    finally:
        ctx.stop()
