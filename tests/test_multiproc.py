"""Multi-controller SPMD execution: a FULL scheduler job
(parallelize -> map -> reduceByKey -> collect) across 2 jax processes
sharing one 8-device mesh (VERDICT r3 #3 — converts SURVEY.md section 2.5
cluster management from protocol-tested to end-to-end).

Reference parity: dpark ran whole jobs across machines via Mesos
(SURVEY.md section 2.1 schedule/executor rows); here every rank runs the
same driver program and host readbacks replicate through
layout.host_read, so scheduler decisions stay identical across ranks.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    yield c
    c.stop()


def test_spmd_full_job_two_processes():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as g
    reason = g._dryrun_spmd_job(nprocs=2, local_devices=4)
    if reason:
        # the known XLA:CPU multi-controller gap, recorded as the
        # stage's fallback_reason: results were still asserted
        # bit-identical on the object path (ISSUE 12 satellite —
        # skip-with-reason, not a raw assert)
        pytest.skip(reason)


def test_spmd_full_job_four_processes():
    """4 controller processes x 2 devices each on one 8-device mesh
    (VERDICT r4 #6: past 2 ranks)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as g
    reason = g._dryrun_spmd_job(nprocs=4, local_devices=2)
    if reason:
        pytest.skip(reason)


def test_host_read_and_put_sharded_single_process(tctx):
    """The multi-controller helpers are the SAME code path single-proc
    jobs use — exercise them directly on the in-process mesh."""
    import numpy as np
    from dpark_tpu.backend.tpu import layout
    tctx.start()
    ex = tctx.scheduler.executor
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(ex.mesh, P(layout.AXIS))
    arr = np.arange(ex.ndev * 4, dtype=np.int32).reshape(ex.ndev, 4)
    dev = layout.put_sharded(arr, sh)
    assert dev.sharding.is_fully_addressable
    back = layout.host_read(dev)
    assert (back == arr).all()
