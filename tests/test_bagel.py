"""Bagel BSP tests: PageRank and shortest path convergence on a mini
graph (reference: tests/test_bagel.py, SURVEY.md section 4)."""

import operator

import pytest

from dpark_tpu.bagel import (Bagel, BasicCombiner, Edge, Message, Vertex,
                             Aggregator)


def make_graph(ctx, links):
    """links: dict id -> list of target ids"""
    n = len(links)
    verts = ctx.parallelize(
        [(i, Vertex(i, 1.0 / n, [Edge(t) for t in targets]))
         for i, targets in links.items()], 2)
    msgs = ctx.parallelize([], 2)
    return verts, msgs, n


GRAPH = {0: [1, 2], 1: [2], 2: [0], 3: [2]}


class PRCompute:
    """Fixed-iteration power method: every vertex stays active for
    `steps` supersteps so rank mass is conserved."""

    def __init__(self, n, damping=0.8, steps=25):
        self.n = n
        self.damping = damping
        self.steps = steps

    def __call__(self, vert, msg_sum, agg, superstep):
        if superstep == 0:
            new_value = vert.value
        else:
            incoming = msg_sum or 0.0
            new_value = (1 - self.damping) / self.n + self.damping * incoming
        active = superstep < self.steps
        v = Vertex(vert.id, new_value, vert.outEdges, active)
        if active and vert.outEdges:
            share = new_value / len(vert.outEdges)
            out = [Message(e.target_id, share) for e in vert.outEdges]
        else:
            out = []
        return (v, out)


def test_pagerank_converges(ctx):
    verts, msgs, n = make_graph(ctx, GRAPH)
    final = Bagel.run(ctx, verts, msgs, PRCompute(n),
                      combiner=BasicCombiner(operator.add))
    ranks = {vid: v.value for vid, v in final.collect()}
    assert len(ranks) == 4
    assert abs(sum(ranks.values()) - 1.0) < 0.02
    # 2 has the most inbound links; 3 has none
    assert ranks[2] == max(ranks.values())
    assert ranks[3] == min(ranks.values())


class SPCompute:
    """Single-source shortest path over unit-weight edges."""

    def __call__(self, vert, mail, agg, superstep):
        best = vert.value
        if mail:
            best = min(best, min(mail))
        if best < vert.value or superstep == 0:
            v = Vertex(vert.id, best, vert.outEdges, False)
            out = [Message(e.target_id, best + 1) for e in vert.outEdges] \
                if best < float("inf") else []
            return (v, out)
        return (Vertex(vert.id, vert.value, vert.outEdges, False), [])


def test_shortest_path(ctx):
    import math
    chain = {0: [1], 1: [2], 2: [3], 3: []}
    inf = float("inf")
    verts = ctx.parallelize(
        [(i, Vertex(i, 0.0 if i == 0 else inf,
                    [Edge(t) for t in targets]))
         for i, targets in chain.items()], 2)
    msgs = ctx.parallelize([], 2)
    final = Bagel.run(ctx, verts, msgs, SPCompute())
    dist = {vid: v.value for vid, v in final.collect()}
    assert dist == {0: 0.0, 1: 1.0, 2: 2.0, 3: 3.0}


class MaxAggregator(Aggregator):
    def createAggregator(self, vert):
        return vert.value

    def mergeAggregators(self, a, b):
        return max(a, b)


def test_aggregator_visible_next_superstep(ctx):
    seen = []

    def compute(vert, mail, agg, superstep):
        if superstep == 1:
            seen.append(agg)
        active = superstep < 1
        return (Vertex(vert.id, vert.value, vert.outEdges, active),
                [Message(vert.id, 0)] if active else [])

    verts = ctx.parallelize(
        [(i, Vertex(i, float(i), [])) for i in range(5)], 2)
    msgs = ctx.parallelize([], 2)
    Bagel.run(ctx, verts, msgs, compute, aggregator=MaxAggregator())
    assert seen and all(a == 4.0 for a in seen)


# ----------------------------------------------------------------------
# fast driver-resident object path (VERDICT r2 ask #4): same semantics
# as the RDD algebra, no per-superstep shuffle jobs
# ----------------------------------------------------------------------

def _run_both_paths(ctx, make_inputs, compute, **kw):
    """The same program through the fast path and the RDD path."""
    import dpark_tpu.bagel as bagel_mod
    verts, msgs = make_inputs()
    fast = dict(Bagel.run(ctx, verts, msgs, compute, **kw).collect())
    was = bagel_mod.FAST_OBJECT_RUN
    bagel_mod.FAST_OBJECT_RUN = False
    try:
        verts, msgs = make_inputs()
        rdd = dict(Bagel.run(ctx, verts, msgs, compute, **kw).collect())
    finally:
        bagel_mod.FAST_OBJECT_RUN = was
    return fast, rdd


def test_fast_path_matches_rdd_path_pagerank(ctx):
    def make():
        verts, msgs, _ = make_graph(ctx, GRAPH)
        return verts, msgs
    fast, rdd = _run_both_paths(
        ctx, make, PRCompute(4), combiner=BasicCombiner(operator.add))
    assert set(fast) == set(rdd)
    for vid in fast:
        assert abs(fast[vid].value - rdd[vid].value) < 1e-12
        assert fast[vid].active == rdd[vid].active


def test_fast_path_matches_rdd_path_sssp(ctx):
    """List-combiner mail, inactive vertices woken by messages, and a
    vertex with no outgoing edges."""
    inf = float("inf")
    chain = {0: [1, 2], 1: [3], 2: [3], 3: []}

    def make():
        verts = ctx.parallelize(
            [(i, Vertex(i, 0.0 if i == 0 else inf,
                        [Edge(t) for t in targets]))
             for i, targets in chain.items()], 2)
        return verts, ctx.parallelize([], 2)

    fast, rdd = _run_both_paths(ctx, make, SPCompute())
    assert {v: fast[v].value for v in fast} \
        == {v: rdd[v].value for v in rdd}


def test_fast_path_drops_unknown_targets(ctx):
    """Messages to ids not in the graph vanish on both paths."""
    def compute(vert, mail, agg, superstep):
        active = superstep < 2
        return (Vertex(vert.id, (vert.value
                                 + (sum(mail) if mail else 0)),
                       vert.outEdges, active),
                [Message(99, 1), Message(1 - vert.id, 1)]
                if active else [])

    def make():
        verts = ctx.parallelize(
            [(i, Vertex(i, 0, [])) for i in range(2)], 2)
        return verts, ctx.parallelize([], 2)

    fast, rdd = _run_both_paths(ctx, make, compute)
    assert {v: fast[v].value for v in fast} \
        == {v: rdd[v].value for v in rdd}


def test_fast_path_initial_messages_and_aggregator(ctx):
    seen = []

    def compute(vert, mail, agg, superstep):
        seen.append(agg)
        val = vert.value + (sum(mail) if mail else 0)
        return (Vertex(vert.id, val, vert.outEdges, False), [])

    def make():
        verts = ctx.parallelize(
            [(i, Vertex(i, float(i), [])) for i in range(4)], 2)
        msgs = ctx.parallelize([(0, 10.0), (0, 5.0), (3, 1.0)], 2)
        return verts, msgs

    fast, rdd = _run_both_paths(ctx, make, compute,
                                aggregator=MaxAggregator())
    assert {v: fast[v].value for v in fast} \
        == {v: rdd[v].value for v in rdd}
    assert fast[0].value == 15.0 and fast[3].value == 4.0
    assert 3.0 in seen                      # aggregator ran on both


def test_fast_path_falls_back_on_id_rebinding(ctx):
    """compute returning a vertex with a different id is only modeled
    by the RDD path (key stays, id attr changes): the fast path must
    detect it and fall back with identical results."""
    def compute(vert, mail, agg, superstep):
        return (Vertex(vert.id + 100, vert.value + 1, [], False), [])

    verts = ctx.parallelize(
        [(i, Vertex(i, float(i), [])) for i in range(3)], 2)
    msgs = ctx.parallelize([], 2)
    out = dict(Bagel.run(ctx, verts, msgs, compute).collect())
    assert sorted(out) == [0, 1, 2]          # keys preserved
    assert all(out[k].id == k + 100 for k in out)


def test_fast_path_schedules_no_superstep_jobs(ctx):
    """The point of the fast path: zero RDD jobs inside the superstep
    loop (the RDD path schedules >= 2 per superstep)."""
    verts, msgs, n = make_graph(ctx, GRAPH)
    ctx.start()
    before = ctx.scheduler._next_job_id
    Bagel.run(ctx, verts, msgs, PRCompute(n, steps=5),
              combiner=BasicCombiner(operator.add))
    jobs = ctx.scheduler._next_job_id - before
    assert jobs <= 3, jobs            # count + two collects, no loop jobs
