"""Bagel BSP tests: PageRank and shortest path convergence on a mini
graph (reference: tests/test_bagel.py, SURVEY.md section 4)."""

import operator

import pytest

from dpark_tpu.bagel import (Bagel, BasicCombiner, Edge, Message, Vertex,
                             Aggregator)


def make_graph(ctx, links):
    """links: dict id -> list of target ids"""
    n = len(links)
    verts = ctx.parallelize(
        [(i, Vertex(i, 1.0 / n, [Edge(t) for t in targets]))
         for i, targets in links.items()], 2)
    msgs = ctx.parallelize([], 2)
    return verts, msgs, n


GRAPH = {0: [1, 2], 1: [2], 2: [0], 3: [2]}


class PRCompute:
    """Fixed-iteration power method: every vertex stays active for
    `steps` supersteps so rank mass is conserved."""

    def __init__(self, n, damping=0.8, steps=25):
        self.n = n
        self.damping = damping
        self.steps = steps

    def __call__(self, vert, msg_sum, agg, superstep):
        if superstep == 0:
            new_value = vert.value
        else:
            incoming = msg_sum or 0.0
            new_value = (1 - self.damping) / self.n + self.damping * incoming
        active = superstep < self.steps
        v = Vertex(vert.id, new_value, vert.outEdges, active)
        if active and vert.outEdges:
            share = new_value / len(vert.outEdges)
            out = [Message(e.target_id, share) for e in vert.outEdges]
        else:
            out = []
        return (v, out)


def test_pagerank_converges(ctx):
    verts, msgs, n = make_graph(ctx, GRAPH)
    final = Bagel.run(ctx, verts, msgs, PRCompute(n),
                      combiner=BasicCombiner(operator.add))
    ranks = {vid: v.value for vid, v in final.collect()}
    assert len(ranks) == 4
    assert abs(sum(ranks.values()) - 1.0) < 0.02
    # 2 has the most inbound links; 3 has none
    assert ranks[2] == max(ranks.values())
    assert ranks[3] == min(ranks.values())


class SPCompute:
    """Single-source shortest path over unit-weight edges."""

    def __call__(self, vert, mail, agg, superstep):
        best = vert.value
        if mail:
            best = min(best, min(mail))
        if best < vert.value or superstep == 0:
            v = Vertex(vert.id, best, vert.outEdges, False)
            out = [Message(e.target_id, best + 1) for e in vert.outEdges] \
                if best < float("inf") else []
            return (v, out)
        return (Vertex(vert.id, vert.value, vert.outEdges, False), [])


def test_shortest_path(ctx):
    import math
    chain = {0: [1], 1: [2], 2: [3], 3: []}
    inf = float("inf")
    verts = ctx.parallelize(
        [(i, Vertex(i, 0.0 if i == 0 else inf,
                    [Edge(t) for t in targets]))
         for i, targets in chain.items()], 2)
    msgs = ctx.parallelize([], 2)
    final = Bagel.run(ctx, verts, msgs, SPCompute())
    dist = {vid: v.value for vid, v in final.collect()}
    assert dist == {0: 0.0, 1: 1.0, 2: 2.0, 3: 3.0}


class MaxAggregator(Aggregator):
    def createAggregator(self, vert):
        return vert.value

    def mergeAggregators(self, a, b):
        return max(a, b)


def test_aggregator_visible_next_superstep(ctx):
    seen = []

    def compute(vert, mail, agg, superstep):
        if superstep == 1:
            seen.append(agg)
        active = superstep < 1
        return (Vertex(vert.id, vert.value, vert.outEdges, active),
                [Message(vert.id, 0)] if active else [])

    verts = ctx.parallelize(
        [(i, Vertex(i, float(i), [])) for i in range(5)], 2)
    msgs = ctx.parallelize([], 2)
    Bagel.run(ctx, verts, msgs, compute, aggregator=MaxAggregator())
    assert seen and all(a == 4.0 for a in seen)
