"""Table DSL + sketches tests (reference: tests/test_table.py style,
SURVEY.md section 4)."""

import pytest


@pytest.fixture()
def sales(ctx):
    rows = [("north", "apple", 3, 1.5),
            ("north", "pear", 2, 2.0),
            ("south", "apple", 5, 1.4),
            ("south", "pear", 1, 2.2),
            ("south", "apple", 2, 1.6)]
    return ctx.parallelize(rows, 2).asTable(
        "region item qty price", name="sales")


def test_select_exprs(sales):
    t = sales.select("item", "qty * price as total")
    got = t.collect()
    assert t.fields == ["item", "total"]
    assert got[0].item == "apple" and abs(got[0].total - 4.5) < 1e-9


def test_where(sales):
    t = sales.where("qty > 2", "region == 'south'")
    assert [r.item for r in t.collect()] == ["apple"]


def test_group_by(sales):
    t = sales.groupBy("region", "sum(qty) as total_qty",
                      "count(*) as n", "avg(price) as avg_price")
    got = {r.region: r for r in t.collect()}
    assert got["north"].total_qty == 5
    assert got["north"].n == 2
    assert got["south"].n == 3
    assert abs(got["south"].avg_price - (1.4 + 2.2 + 1.6) / 3) < 1e-9


def test_group_by_min_max(sales):
    t = sales.groupBy("item", "min(price) as lo", "max(price) as hi")
    got = {r.item: r for r in t.collect()}
    assert got["apple"].lo == 1.4 and got["apple"].hi == 1.6
    assert got["pear"].lo == 2.0 and got["pear"].hi == 2.2


def test_global_aggregate(sales):
    t = sales.select("sum(qty) as total", "count(*) as n")
    (row,) = t.collect()
    assert row.total == 13 and row.n == 5


def test_sort_top(sales):
    t = sales.sort("qty", reverse=True)
    rows = t.collect()
    assert [r.qty for r in rows] == [5, 3, 2, 2, 1]
    top2 = sales.top(2, key="qty")
    assert [r.qty for r in top2] == [5, 3]


def test_join(ctx, sales):
    prices = ctx.parallelize(
        [("apple", "fruit"), ("pear", "fruit")], 2).asTable(
        "item category", name="cat")
    j = sales.select("item", "qty").join(prices, on="item")
    got = j.collect()
    assert len(got) == 5
    assert all(r.category == "fruit" for r in got)


def test_adcount(ctx):
    t = ctx.parallelize([(i % 100, i) for i in range(10000)], 4) \
           .asTable("k v")
    (row,) = t.select("adcount(k) as distinct_keys").collect()
    assert 90 <= row.distinct_keys <= 110


def test_rdd_adcount_accuracy(ctx):
    n = ctx.parallelize(list(range(5000)), 4).adcount()
    assert 4500 <= n <= 5500


def test_hotcounter():
    from dpark_tpu.hotcounter import HotCounter
    hc = HotCounter(capacity=50)
    for i in range(10000):
        hc.add(i % 200)             # uniform noise
    for _ in range(500):
        hc.add("hot1")
    for _ in range(300):
        hc.add("hot2")
    top = [v for v, _ in hc.top(2)]
    assert "hot1" in top and "hot2" in top


def test_hyperloglog_merge():
    from dpark_tpu.hyperloglog import HyperLogLog
    a, b = HyperLogLog(), HyperLogLog()
    for i in range(3000):
        a.add(i)
    for i in range(2000, 6000):
        b.add(i)
    a.update(b)
    assert 5400 <= len(a) <= 6600


def test_ctx_table_roundtrip(ctx, tmp_path):
    rows = [(i, i * i) for i in range(100)]
    ctx.parallelize(rows, 3).saveAsTableFile(str(tmp_path / "t"))
    t = ctx.table(str(tmp_path / "t"), fields="a b")
    assert t.count() == 100
    got = t.where("a == 7").collect()
    assert got[0].b == 49


def test_sql_execute(ctx, sales):
    got = ctx.sql(
        "select region, sum(qty) as total from sales group by region",
        sales=sales).collect()
    assert sorted((r.region, r.total) for r in got) == [
        ("north", 5), ("south", 8)]

    rows = ctx.sql(
        "select item, qty from sales where region == 'south' "
        "order by qty desc limit 2", sales=sales)
    assert [r.qty for r in rows] == [5, 2]

    allrows = ctx.sql("select * from sales", sales=sales).collect()
    assert len(allrows) == 5

    with pytest.raises(ValueError):
        ctx.sql("delete from sales", sales=sales)
    with pytest.raises(ValueError):
        ctx.sql("select * from nope", sales=sales)


def test_sql_edge_cases(ctx, sales):
    # ORDER BY a column the projection drops
    rows = ctx.sql("select item from sales order by qty desc limit 2",
                   sales=sales)
    assert [r.item for r in rows] == ["apple", "apple"]
    # SELECT order respected in GROUP BY output
    got = ctx.sql(
        "select sum(qty) as q, region from sales group by region",
        sales=sales).collect()
    assert got[0]._fields == ("q", "region")
    # clause keyword inside a string literal
    none = ctx.sql(
        "select * from sales where item == 'a group by b'",
        sales=sales).collect()
    assert none == []
    # table named like the positional parameter
    assert ctx.sql("select * from query", query=sales).count() == 5
    # non-aggregate select column that is not a group key
    with pytest.raises(ValueError):
        ctx.sql("select price, sum(qty) from sales group by region",
                sales=sales)


def test_sql_order_by_variants(ctx, sales):
    rows = ctx.sql("select qty * 2 as d from sales order by qty asc "
                   "limit 2", sales=sales)
    assert [r.d for r in rows] == [2, 4]
    rows = ctx.sql("select qty * 2 from sales order by qty * 2 desc "
                   "limit 1", sales=sales)
    assert rows[0][0] == 10
    got = ctx.sql(r"select * from sales where item == 'don\'t group by'",
                  sales=sales).collect()
    assert got == []


@pytest.mark.mesh
def test_sql_group_by_rides_device_shuffle():
    """VERDICT r3 #8: ctx.sql GROUP BY sum/count/avg/min/max compiles
    onto the monoid device shuffle (shuffle_store populated, wire bytes
    moved) — the Table DSL inherits the core's speed, with results
    matching the host-computed expectation exactly."""
    from dpark_tpu import DparkContext
    tctx = DparkContext("tpu")
    tctx.start()
    try:
        rows = [(i % 7, i, i * 2) for i in range(2000)]
        t = tctx.table(tctx.parallelize(rows, 8), ["g", "x", "y"])
        res = tctx.sql(
            "select g, sum(x) as sx, count(*) as c, avg(y) as ay, "
            "min(x) as mn, max(y) as mx from t group by g order by g",
            t=t).collect()
        ex = tctx.scheduler.executor
        assert ex.shuffle_store, "SQL group-by did not ride the device"
        assert ex.exchange_wire_bytes > 0, "no device exchange ran"
        exp = {}
        for g, x, y in rows:
            s, c, sy, mn, mx = exp.get(g, (0, 0, 0, x, y))
            exp[g] = (s + x, c + 1, sy + y, min(mn, x), max(mx, y))
        assert len(res) == 7
        for r in res:
            s, c, sy, mn, mx = exp[r.g]
            assert (r.sx, r.c, r.mn, r.mx) == (s, c, mn, mx)
            assert abs(r.ay - sy / c) < 1e-9
    finally:
        tctx.stop()


@pytest.mark.mesh
def test_table_join_rides_device():
    """Numeric table equi-joins inherit the array-path join source:
    every stage of select-over-join runs on the device (VERDICT r3 #8
    sibling — the Table DSL inherits the core's speed)."""
    from dpark_tpu import DparkContext
    tctx = DparkContext("tpu")
    tctx.start()
    li = tctx.parallelize(
        [(i % 500, i % 7, (i % 11) * 10) for i in range(20000)], 8) \
        .asTable(["okey", "qty", "price"], "lineitem")
    od = tctx.parallelize([(i, i % 3) for i in range(500)], 8) \
        .asTable(["okey", "prio"], "orders")
    out = li.join(od, on="okey").select("okey", "qty", "prio").collect()
    assert len(out) == 20000
    kinds = set()
    for rec in tctx.scheduler.history:
        for s in rec.get("stage_info", []):
            kinds.add(s.get("kind"))
    assert kinds == {"array"}, kinds
    tctx.stop()


def test_sql_join_having_agg_exprs(ctx, sales):
    """r5 SQL front: JOIN ... ON, HAVING, and aggregate expressions in
    SELECT/HAVING (VERDICT r4 #6)."""
    dim = ctx.table(ctx.parallelize(
        [("apple", 1), ("pear", 2), ("plum", 3)], 2), ["item", "code"])
    rows = ctx.sql("select item, qty, code from sales join dim on item "
                   "order by qty limit 10", sales=sales, dim=dim)
    assert all(hasattr(r, "code") for r in rows)
    got = {(r.item, r.code) for r in rows}
    assert got <= {("apple", 1), ("pear", 2), ("plum", 3)}

    t = ctx.sql("select item, sum(qty) as s from sales group by item "
                "having sum(qty) > 3 order by s desc", sales=sales)
    res = t.collect()
    assert all(r.s > 3 for r in res)
    assert [r.s for r in res] == sorted((r.s for r in res),
                                        reverse=True)

    t = ctx.sql("select item, sum(qty) * 2 + count(*) as score from "
                "sales group by item", sales=sales)
    base = {}
    for r in sales.collect():
        s, c = base.get(r.item, (0, 0))
        base[r.item] = (s + r.qty, c + 1)
    exp = {k: s * 2 + c for k, (s, c) in base.items()}
    assert {r.item: r.score for r in t.collect()} == exp

    # a.col = b.col spelling; mismatched names refuse
    rows = ctx.sql("select item, code from sales join dim on "
                   "sales.item = dim.item limit 3",
                   sales=sales, dim=dim)
    assert rows
    import pytest as _pytest
    with _pytest.raises(ValueError):
        ctx.sql("select * from sales join dim on sales.item = dim.code",
                sales=sales, dim=dim)
    with _pytest.raises(ValueError):
        ctx.sql("select item from sales having sum(qty) > 1",
                sales=sales)


@pytest.mark.mesh
def test_sql_join_group_rides_device():
    """SQL JOIN -> GROUP BY -> HAVING runs its join and aggregation on
    the array path (the join lowers to the device join source)."""
    from dpark_tpu import DparkContext
    tctx = DparkContext("tpu")
    tctx.start()
    try:
        li = tctx.parallelize(
            [(i % 200, (i % 7) + 1) for i in range(8000)], 8) \
            .asTable(["okey", "qty"], "li")
        od = tctx.parallelize([(i, i % 3) for i in range(200)], 8) \
            .asTable(["okey", "prio"], "od")
        t = tctx.sql(
            "select prio, sum(qty) as s, sum(qty) * 1.0 / count(*) "
            "as aq from li join od on okey group by prio "
            "having count(*) > 10 order by prio", li=li, od=od)
        res = t.collect()
        exp = {}
        od_map = {i: i % 3 for i in range(200)}
        for i in range(8000):
            p = od_map[i % 200]
            s, c = exp.get(p, (0, 0))
            exp[p] = (s + (i % 7) + 1, c + 1)
        assert [(r.prio, r.s) for r in res] \
            == sorted((p, s) for p, (s, c) in exp.items() if c > 10)
        for r in res:
            s, c = exp[r.prio]
            assert abs(r.aq - s / c) < 1e-9
        kinds = set()
        for rec in tctx.scheduler.history:
            for s_ in rec.get("stage_info", []):
                if rec.get("parts") == 1:
                    continue
                kinds.add((s_["rdd"], s_.get("kind")))
        assert ("CoGroupedRDD", "array") not in kinds
        ex = tctx.scheduler.executor
        assert ex.shuffle_store, "SQL join+group did not ride the device"
        arr = {v for _, v in kinds}
        assert "array" in arr, kinds
    finally:
        tctx.stop()
