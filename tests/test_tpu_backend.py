"""TPU master parity: every test asserts `-m tpu` output == local-master
semantics on the same program (SURVEY.md section 4 implication), running on
the 8-virtual-CPU-device mesh from conftest."""

import pytest

pytestmark = pytest.mark.mesh    # full-mesh collectives (see conftest)


@pytest.fixture()
def tctx():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu")
    c.start()
    yield c
    c.stop()


def _used_array_path(tctx):
    return len(tctx.scheduler.executor.shuffle_store) > 0


def test_parallelize_collect_roundtrip(tctx):
    data = list(range(100))
    assert tctx.parallelize(data, 8).collect() == data


def test_map_filter_fused(tctx):
    r = tctx.parallelize(list(range(64)), 8)
    got = r.map(lambda x: x * 3).filter(lambda x: x % 2 == 0).collect()
    assert got == [x * 3 for x in range(64) if (x * 3) % 2 == 0]


def test_reduce_by_key_device_shuffle(tctx):
    pairs = [(i % 13, i) for i in range(1000)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    expect = {}
    for k, v in pairs:
        expect[k] = expect.get(k, 0) + v
    assert got == expect
    assert _used_array_path(tctx)


def test_reduce_by_key_matches_local(tctx):
    from dpark_tpu import DparkContext
    pairs = [((i * 7919) % 101, i % 17) for i in range(5000)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    lctx = DparkContext("local")
    expect = dict(lctx.parallelize(pairs, 8)
                  .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == expect


def test_negative_and_large_keys(tctx):
    pairs = [(k, 1) for k in
             [-1, -2, 0, 2**30, -(2**30), 7, -7] * 10]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {-1: 10, -2: 10, 0: 10, 2**30: 10,
                   -(2**30): 10, 7: 10, -7: 10}


def test_skewed_keys_multi_round(tctx):
    # one dominant key forces slot overflow -> multi-round exchange
    pairs = [(0, 1)] * 3000 + [(i, 1) for i in range(1, 50)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got[0] == 3000
    assert all(got[i] == 1 for i in range(1, 50))


def test_map_after_shuffle(tctx):
    pairs = [(i % 5, 1) for i in range(100)]
    got = sorted(tctx.parallelize(pairs, 8)
                 .reduceByKey(lambda a, b: a + b, 8)
                 .map(lambda kv: (kv[0], kv[1] * 10)).collect())
    assert got == [(k, 200) for k in range(5)]


def test_chained_shuffles(tctx):
    pairs = [(i % 10, 1) for i in range(400)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8)
               .map(lambda kv: (kv[0] % 2, kv[1]))
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {0: 200, 1: 200}


def test_float_values(tctx):
    pairs = [(i % 4, float(i) * 0.5) for i in range(100)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    expect = {}
    for k, v in pairs:
        expect[k] = expect.get(k, 0.0) + v
    for k in expect:
        assert abs(got[k] - expect[k]) < 1e-3


def test_tuple_values_combine(tctx):
    # average via (sum, count) combiners — tuple-valued records
    pairs = [(i % 3, (i, 1)) for i in range(90)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: (a[0] + b[0], a[1] + b[1]), 8)
               .collect())
    assert got[0][1] == 30 and got[1][1] == 30 and got[2][1] == 30
    assert sum(v[0] for v in got.values()) == sum(range(90))


def test_untraceable_falls_back(tctx):
    # string records cannot ride the array path; result must still be right
    r = tctx.parallelize([("a", 1), ("b", 2), ("a", 3)], 2)
    got = dict(r.reduceByKey(lambda a, b: a + b).collect())
    assert got == {"a": 4, "b": 2}


def test_side_effect_lambda_falls_back(tctx):
    seen = []
    r = tctx.parallelize([(1, 1), (2, 2)], 2)
    got = dict(r.reduceByKey(lambda a, b: (seen.append(1), a + b)[1])
               .collect())
    assert got == {1: 1, 2: 2}


def test_hbm_to_host_bridge(tctx):
    """Device-written shuffle consumed by an untraceable downstream stage
    (mapPartitions is object-path-only) — must read via the HBM bridge."""
    pairs = [(i % 6, 1) for i in range(600)]
    r = (tctx.parallelize(pairs, 8)
         .reduceByKey(lambda a, b: a + b, 8)
         .mapPartitions(lambda it: [sorted(it)]))
    parts = r.collect()
    flat = [kv for part in parts for kv in part]
    assert dict(flat) == {k: 100 for k in range(6)}


def test_count_and_take_on_device_pipeline(tctx):
    r = tctx.parallelize([(i % 11, 1) for i in range(800)], 8) \
            .reduceByKey(lambda a, b: a + b, 8)
    assert r.count() == 11
    assert len(r.take(5)) == 5


def test_non_divisible_partitions_fall_back(tctx):
    # 5 partitions on an 8-device mesh -> object path, same answer
    pairs = [(i % 3, 1) for i in range(50)]
    got = dict(tctx.parallelize(pairs, 5)
               .reduceByKey(lambda a, b: a + b, 5).collect())
    assert got == {0: 17, 1: 17, 2: 16}


def test_large_sum_no_overflow(tctx):
    """Values summing past 2**31 must not wrap (int64 device path)."""
    pairs = [(1, 2_000_000_000)] * 8
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {1: 16_000_000_000}


def test_int32_max_key_not_dropped(tctx):
    """INT32_MAX is a legitimate key, not padding."""
    pairs = [(2**31 - 1, 1)] * 8 + [(5, 2)] * 8
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {2**31 - 1: 8, 5: 16}


def test_int64_sentinel_key_falls_back(tctx):
    """The one reserved key value (2**63-1) takes the host path."""
    pairs = [(2**63 - 1, 1)] * 4 + [(3, 1)] * 4
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {2**63 - 1: 4, 3: 4}


def test_hbm_eviction_triggers_lineage_recovery(tctx):
    from dpark_tpu import conf
    old = conf.SHUFFLE_HBM_BUDGET
    conf.SHUFFLE_HBM_BUDGET = 1          # evict everything beyond newest
    try:
        r1 = tctx.parallelize([(i % 4, 1) for i in range(200)], 8) \
                 .reduceByKey(lambda a, b: a + b, 8)
        assert dict(r1.collect()) == {k: 50 for k in range(4)}
        r2 = tctx.parallelize([(i % 3, 1) for i in range(90)], 8) \
                 .reduceByKey(lambda a, b: a + b, 8)
        assert dict(r2.collect()) == {k: 30 for k in range(3)}
        # r1's HBM store may be gone; a new action must still succeed
        # (FetchFailed -> parent stage recompute)
        assert dict(r1.collect()) == {k: 50 for k in range(4)}
    finally:
        conf.SHUFFLE_HBM_BUDGET = old


def test_columnar_parallelize_device(tctx):
    import numpy as np
    n = 100_000
    keys = (np.arange(n, dtype=np.int64) * 2654435761) % 1000
    vals = np.ones(n, dtype=np.int64)
    from dpark_tpu import Columns
    got = dict(tctx.parallelize(Columns(keys, vals), 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert len(got) == 1000
    assert sum(got.values()) == n
    assert _used_array_path(tctx)


def test_columnar_parallelize_object_path_parity(ctx):
    import numpy as np
    keys = np.array([1, 2, 1, 3], dtype=np.int64)
    vals = np.array([10, 20, 30, 40], dtype=np.int64)
    from dpark_tpu import Columns
    got = dict(ctx.parallelize(Columns(keys, vals), 2)
               .reduceByKey(lambda a, b: a + b).collect())
    assert got == {1: 40, 2: 20, 3: 40}
    single = ctx.parallelize(np.arange(5), 2).map(lambda x: x * 2).collect()
    assert single == [0, 2, 4, 6, 8]


def test_sortbykey_on_device(tctx):
    import random
    from dpark_tpu import DparkContext
    rng = random.Random(9)
    pairs = [(rng.randint(-10000, 10000), i) for i in range(4000)]
    r = tctx.parallelize(pairs, 8)
    got = r.sortByKey(numSplits=8).collect()
    assert [k for k, _ in got] == sorted(k for k, _ in pairs)
    assert _used_array_path(tctx)
    got_desc = r.sortByKey(ascending=False, numSplits=8).collect()
    assert [k for k, _ in got_desc] == sorted(
        (k for k, _ in pairs), reverse=True)


def test_sortbykey_float_keys_device(tctx):
    import random
    rng = random.Random(4)
    pairs = [(rng.random() * 100 - 50, i) for i in range(2000)]
    got = tctx.parallelize(pairs, 8).sortByKey(numSplits=8).collect()
    ks = [k for k, _ in got]
    assert all(abs(a - b) < 1e-4 for a, b in
               zip(ks, sorted(k for k, _ in pairs)))


def test_groupbykey_on_device(tctx):
    pairs = [(i % 7, i) for i in range(700)]
    got = dict(tctx.parallelize(pairs, 8).groupByKey(8).collect())
    assert set(got) == set(range(7))
    for k in range(7):
        assert sorted(got[k]) == [i for i in range(700) if i % 7 == k]
    assert _used_array_path(tctx)


def test_partition_by_device_then_host_op(tctx):
    """partitionBy on device, then an untraceable op via the HBM bridge."""
    pairs = [(i, i * 2) for i in range(400)]
    r = tctx.parallelize(pairs, 8).partitionBy(8) \
            .mapPartitions(lambda it: [len(list(it))])
    counts = r.collect()
    assert sum(counts) == 400


def test_distinct_on_device(tctx):
    data = [i % 50 for i in range(2000)]
    got = sorted(tctx.parallelize(data, 8).distinct(8).collect())
    assert got == list(range(50))


def test_sentinel_key_in_range_sort_falls_back(tctx):
    """INT64_MAX key must not be silently dropped by device sortByKey."""
    pairs = [(i, i) for i in range(100, 1000)] + [(2**63 - 1, 111)]
    got = tctx.parallelize(pairs, 8).sortByKey(numSplits=8).collect()
    assert got[-1] == (2**63 - 1, 111)
    assert len(got) == len(pairs)


def test_inf_float_key_falls_back(tctx):
    pairs = [(float(i), i) for i in range(50)] + [(float("inf"), -1)]
    got = tctx.parallelize(pairs, 8).sortByKey(numSplits=8).collect()
    assert got[-1] == (float("inf"), -1)


def test_cogroup_device_exchange(tctx):
    a = tctx.parallelize([(i % 20, i) for i in range(400)], 8)
    b = tctx.parallelize([(i % 20, i * 3) for i in range(200)], 8)
    got = dict(a.cogroup(b, numSplits=8).collect())
    assert set(got) == set(range(20))
    for k in range(20):
        assert sorted(got[k][0]) == [i for i in range(400) if i % 20 == k]
        assert sorted(got[k][1]) == [i * 3 for i in range(200)
                                     if i % 20 == k]


def test_join_device_exchange_matches_local(tctx):
    from dpark_tpu import DparkContext
    a_pairs = [(i % 30, i) for i in range(300)]
    b_pairs = [(i % 30, -i) for i in range(150)]
    a = tctx.parallelize(a_pairs, 8)
    b = tctx.parallelize(b_pairs, 8)
    got = sorted(a.join(b, 8).collect())
    lctx = DparkContext("local")
    expect = sorted(lctx.parallelize(a_pairs, 8)
                    .join(lctx.parallelize(b_pairs, 8), 8).collect())
    assert got == expect


def test_hbm_result_cache(tctx):
    """A cached device result is reused: the second action consumes the
    HBM batch instead of re-ingesting, and downstream stages chain off
    it."""
    pairs = [(i % 9, 1) for i in range(900)]
    r = tctx.parallelize(pairs, 8).reduceByKey(lambda a, b: a + b, 8) \
            .cache()
    assert dict(r.collect()) == {k: 100 for k in range(9)}
    ex = tctx.scheduler.executor
    assert r.id in set(ex.result_cache_ids())
    # downstream of the cached batch
    doubled = dict(r.map(lambda kv: (kv[0], kv[1] * 2)).collect())
    assert doubled == {k: 200 for k in range(9)}
    assert r.count() == 9
    r.unpersist()
    assert r.id not in set(ex.result_cache_ids())
    # still correct after unpersist (recompute)
    assert r.count() == 9


def test_fewer_reduce_partitions_than_devices(tctx):
    """R < ndev rides the mesh (extra devices idle), exact results."""
    pairs = [(i % 6, 1) for i in range(600)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 3).collect())
    assert got == {k: 100 for k in range(6)}
    assert _used_array_path(tctx)
    srt = tctx.parallelize([(9 - i, i) for i in range(10)] * 10, 8) \
              .sortByKey(numSplits=4).collect()
    assert [k for k, _ in srt] == sorted(k for k in
                                         [9 - i for i in range(10)] * 10)


def test_cached_sentinel_key_falls_back(tctx):
    """A cached RDD containing the sentinel key still shuffles correctly
    (host path), not silently dropping the row."""
    pairs = [(2**63 - 1, 1), (5, 1)] * 4
    r = tctx.parallelize(pairs, 8).cache()
    assert sorted(r.collect()) == sorted(pairs)
    got = dict(r.reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {2**63 - 1: 4, 5: 4}


def test_hbm_budget_shared_across_tiers(tctx):
    from dpark_tpu import conf
    ex = tctx.scheduler.executor
    old = conf.SHUFFLE_HBM_BUDGET
    conf.SHUFFLE_HBM_BUDGET = 1
    try:
        r1 = tctx.parallelize([(i % 4, 1) for i in range(400)], 8) \
                 .reduceByKey(lambda a, b: a + b, 8).cache()
        assert dict(r1.collect()) == {k: 100 for k in range(4)}
        r2 = tctx.parallelize([(i % 2, 1) for i in range(100)], 8) \
                 .reduceByKey(lambda a, b: a + b, 8).cache()
        assert dict(r2.collect()) == {0: 50, 1: 50}
        total = ex._store_bytes + ex._result_bytes
        # over-budget entries were evicted down to the newest survivors
        assert len(ex.shuffle_store) + len(ex.result_cache) <= 2
        # double-collect must not double-count bytes
        before = ex._result_bytes
        assert dict(r2.collect()) == {0: 50, 1: 50}
        assert ex._result_bytes == before
    finally:
        conf.SHUFFLE_HBM_BUDGET = old


def test_dstream_batches_reuse_compiled_programs(tctx):
    """Per-batch jobs hit the structural jit cache: after batch 1, later
    batches compile nothing new (SURVEY.md 7.2 item 5)."""
    import operator
    from dpark_tpu.dstream import StreamingContext
    ssc = StreamingContext(tctx, 1.0)
    out = []
    batches = [[(i % 5, 1) for i in range(64)] for _ in range(4)]
    q = ssc.queueStream(batches)
    q.reduceByKey(operator.add, 8).collect_batches(out)
    tctx.start()
    ssc.zero_time = 0.0
    ssc.run_batch(1.0)
    compiled_after_first = len(tctx.scheduler.executor._compiled)
    for k in (2, 3, 4):
        ssc.run_batch(float(k))
    assert len(out) == 4
    expect = {j: 13 if j < 4 else 12 for j in range(5)}
    assert all(dict(v) == expect for _, v in out)
    assert len(tctx.scheduler.executor._compiled) == compiled_after_first


def test_streamed_shuffle_out_of_core(tctx):
    """Columnar input above the chunk threshold reduces in waves; result
    identical to the in-core path."""
    import numpy as np
    from dpark_tpu import Columns, conf
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 1000          # force ~4 waves
    try:
        n = 60_000
        i = np.arange(n, dtype=np.int64)
        keys = (i * 2654435761) % 37
        vals = np.ones(n, dtype=np.int64)
        got = dict(tctx.parallelize(Columns(keys, vals), 8)
                   .reduceByKey(lambda a, b: a + b, 8).collect())
        expect = {}
        for k in np.unique(keys):
            expect[int(k)] = int((keys == k).sum())
        assert got == expect
        sid = list(tctx.scheduler.executor.shuffle_store)[-1]
        assert tctx.scheduler.executor.shuffle_store[sid].get(
            "pre_reduced")
    finally:
        conf.STREAM_CHUNK_ROWS = old


def test_streamed_shuffle_bridge_to_host(tctx):
    """A host-path stage downstream of a streamed shuffle reads the
    pre-reduced state through the export bridge."""
    import numpy as np
    from dpark_tpu import Columns, conf
    old = conf.STREAM_CHUNK_ROWS
    conf.STREAM_CHUNK_ROWS = 500
    try:
        n = 4_000
        i = np.arange(n, dtype=np.int64)
        keys = i % 11
        vals = np.ones(n, dtype=np.int64)
        r = tctx.parallelize(Columns(keys, vals), 8) \
                .reduceByKey(lambda a, b: a + b, 8) \
                .mapPartitions(lambda it: [sorted(it)])
        flat = [kv for part in r.collect() for kv in part]
        assert dict(flat) == {k: n // 11 + (1 if k < n % 11 else 0)
                              for k in range(11)}
    finally:
        conf.STREAM_CHUNK_ROWS = old


def test_device_join_expansion(tctx):
    """a.join(b) expands pairs entirely on device, matching local."""
    from dpark_tpu import DparkContext
    a_pairs = [(i % 12, i) for i in range(240)]
    b_pairs = [(i % 12, -i) for i in range(120)]
    got = sorted(tctx.parallelize(a_pairs, 8)
                 .join(tctx.parallelize(b_pairs, 8), 8).collect())
    lctx = DparkContext("local")
    expect = sorted(lctx.parallelize(a_pairs, 8)
                    .join(lctx.parallelize(b_pairs, 8), 8).collect())
    assert got == expect
    assert len(got) == 240 * 120 // 12


def test_device_join_disjoint_and_skew(tctx):
    a = tctx.parallelize([(1, "no")] * 0 + [(k, k) for k in range(10)], 8)
    b = tctx.parallelize([(k + 100, k) for k in range(10)], 8)
    assert a.join(b, 8).collect() == []          # disjoint keys
    # heavy skew: one key with many matches on both sides
    aa = tctx.parallelize([(7, i) for i in range(50)] + [(1, 0)], 8)
    bb = tctx.parallelize([(7, -i) for i in range(40)] + [(2, 0)], 8)
    got = aa.join(bb, 8).collect()
    assert len(got) == 50 * 40
    assert all(k == 7 for k, _ in got)


def test_device_join_tuple_values(tctx):
    a = tctx.parallelize([(i % 5, (i, i * 2)) for i in range(50)], 8)
    b = tctx.parallelize([(i % 5, float(i)) for i in range(25)], 8)
    got = sorted(a.join(b, 8).collect())
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    expect = sorted(
        lctx.parallelize([(i % 5, (i, i * 2)) for i in range(50)], 8)
        .join(lctx.parallelize([(i % 5, float(i)) for i in range(25)], 8),
              8).collect())
    assert got == expect


def test_tuple_key_join_rides_device(tctx):
    """Composite (tuple) keys now ride the device join end to end (the
    lexicographic key-match kernels): exact results vs the local golden
    model, with the join-source stage all-array."""
    a = tctx.parallelize([((i % 3, i % 2), i) for i in range(24)], 8)
    b = tctx.parallelize([((i % 3, i % 2), -i) for i in range(12)], 8)
    got = sorted(a.join(b, 8).collect())
    from dpark_tpu import DparkContext
    lctx = DparkContext("local")
    expect = sorted(
        lctx.parallelize([((i % 3, i % 2), i) for i in range(24)], 8)
        .join(lctx.parallelize([((i % 3, i % 2), -i) for i in range(12)],
                               8), 8).collect())
    assert got == expect
    kinds = _stage_kinds(tctx)
    assert kinds.get("FlatMappedValuesRDD") == "array", kinds


def test_tuple_key_all_array_stage_kinds(tctx):
    """The ISSUE 3 acceptance shape: reduceByKey / groupByKey /
    sortByKey over 2-int-tuple keys run with ALL-ARRAY stage kinds (no
    object fallback — tuple keys were the widest silent host-fallback
    trigger), with exact parity vs the local golden model."""
    import random
    from dpark_tpu import DparkContext
    rng = random.Random(21)
    data = [((rng.randint(0, 40), rng.randint(-7, 7)),
             rng.randint(-1000, 1000)) for _ in range(4000)]
    lctx = DparkContext("local")

    rt = sorted(tctx.parallelize(data, 8)
                .reduceByKey(lambda a, b: a + b, 8).collect())
    rl = sorted(lctx.parallelize(data, 8)
                .reduceByKey(lambda a, b: a + b, 8).collect())
    assert rt == rl
    kinds = _stage_kinds(tctx)
    assert set(kinds.values()) == {"array"}, kinds

    gt = sorted((k, sorted(v)) for k, v in
                tctx.parallelize(data, 8).groupByKey(8).collect())
    gl = sorted((k, sorted(v)) for k, v in
                lctx.parallelize(data, 8).groupByKey(8).collect())
    assert gt == gl
    kinds = _stage_kinds(tctx)
    assert set(kinds.values()) == {"array"}, kinds

    st = tctx.parallelize(data, 8).sortByKey(numSplits=8).collect()
    sl = lctx.parallelize(data, 8).sortByKey(numSplits=8).collect()
    assert [k for k, _ in st] == [k for k, _ in sl]
    kinds = _stage_kinds(tctx)
    assert set(kinds.values()) == {"array"}, kinds
    # descending too (the reversal keeps the lexicographic order)
    sd = tctx.parallelize(data, 8).sortByKey(
        ascending=False, numSplits=8).collect()
    ld = lctx.parallelize(data, 8).sortByKey(
        ascending=False, numSplits=8).collect()
    assert [k for k, _ in sd] == [k for k, _ in ld]


def test_tuple_key_partition_matches_host_partitioner(tctx):
    """Device-routed tuple keys land in the partition the HOST
    HashPartitioner computes (the pair-extended phash contract) —
    lookup() trusts get_partition to find device-shuffled rows."""
    from dpark_tpu.dependency import HashPartitioner
    data = [((i % 11, i % 3), i) for i in range(600)]
    r = tctx.parallelize(data, 8).reduceByKey(lambda a, b: a + b, 8)
    expect = {}
    for k, v in data:
        expect[k] = expect.get(k, 0) + v
    for key in list(expect)[:8]:
        assert r.lookup(key) == [expect[key]], key


def test_tuple_key_sentinel_column_falls_back(tctx):
    """A tuple key whose FIRST column carries the reserved sentinel
    value still produces exact results (host path, like scalar keys)."""
    pairs = [((2**63 - 1, 1), 1)] * 4 + [((3, 1), 1)] * 4
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    assert got == {(2**63 - 1, 1): 4, (3, 1): 4}


def test_nested_tuple_key_stays_on_host(tctx):
    """Only FLAT numeric tuples ride the device: a nested key keeps the
    object path and exact results."""
    pairs = [(((i % 3, i % 2), i % 2), 1) for i in range(48)]
    got = dict(tctx.parallelize(pairs, 8)
               .reduceByKey(lambda a, b: a + b, 8).collect())
    expect = {}
    for k, v in pairs:
        expect[k] = expect.get(k, 0) + v
    assert got == expect
    kinds = _stage_kinds(tctx)
    assert kinds.get("ShuffledRDD") != "array", kinds


def test_single_device_mesh_fast_path():
    """ndev == 1 (a real single-chip config): the exchange fast path
    returns the bucketized prefix directly — no collective program, no
    narrowing probe, zero wire bytes — with full parity on the in-core
    reduce, the spilled sort stream, and the r > mesh pre-reduce
    stream.  Runs in a subprocess: the suite's mesh is pinned to 8
    virtual devices at import time."""
    import os
    import subprocess
    import sys
    script = r'''
import os
os.environ["DPARK_TPU_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import numpy as np
from dpark_tpu import DparkContext, Columns, conf

ctx = DparkContext("tpu"); ctx.start()
ex = ctx.scheduler.executor
assert ex.ndev == 1, ex.ndev
n = 60000
i = np.arange(n, dtype=np.int64)
got = dict(ctx.parallelize(Columns((i*7) % 1000, i % 5), 1)
           .reduceByKey(lambda a, b: a + b, 1).collect())
expect = {}
for k, v in zip(((i*7) % 1000).tolist(), (i % 5).tolist()):
    expect[k] = expect.get(k, 0) + v
assert got == expect
conf.STREAM_CHUNK_ROWS = 8000
keys = np.random.RandomState(3).randint(0, 10**6, n).astype(np.int64)
got2 = ctx.parallelize(Columns(keys, i), 1).sortByKey(numSplits=6).collect()
assert [k for k, _ in got2] == sorted(keys.tolist())
got3 = dict(ctx.parallelize(Columns((i*13) % 37, i % 7), 1)
            .reduceByKey(lambda a, b: a + b, 6).collect())
expect3 = {}
for k, v in zip(((i*13) % 37).tolist(), (i % 7).tolist()):
    expect3[k] = expect3.get(k, 0) + v
assert got3 == expect3
assert ex.exchange_wire_bytes == 0, ex.exchange_wire_bytes
ctx.stop()
print("OK_SINGLE_DEV")
'''
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=280)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK_SINGLE_DEV" in out.stdout


def test_egest_narrowed_wire_parity(tctx):
    """Large int64 results whose values fit int32 ride D2H narrowed
    (the 37 MB/s tunnel guard, VERDICT r3 #6) — results identical."""
    from dpark_tpu import conf
    old = conf.EGEST_NARROW_MIN_BYTES
    conf.EGEST_NARROW_MIN_BYTES = 1           # force the probe at toy size
    try:
        pairs = [(i % 50, i) for i in range(4000)]
        got = dict(tctx.parallelize(pairs, 8)
                   .reduceByKey(lambda a, b: a + b, 8).collect())
        exp = {}
        for k, v in pairs:
            exp[k] = exp.get(k, 0) + v
        assert got == exp
    finally:
        conf.EGEST_NARROW_MIN_BYTES = old


def test_egest_narrow_skipped_for_big_values(tctx):
    """Values beyond int32 range must NOT be narrowed."""
    from dpark_tpu import conf
    old = conf.EGEST_NARROW_MIN_BYTES
    conf.EGEST_NARROW_MIN_BYTES = 1
    try:
        big = 1 << 40
        pairs = [(i % 10, big + i) for i in range(100)]
        got = dict(tctx.parallelize(pairs, 8)
                   .reduceByKey(lambda a, b: max(a, b), 8).collect())
        exp = {}
        for k, v in pairs:
            exp[k] = max(exp.get(k, 0), v)
        assert got == exp
    finally:
        conf.EGEST_NARROW_MIN_BYTES = old


def test_egest_oversize_warning(tctx, caplog):
    """collect() beyond EGEST_WARN_BYTES logs the reduce-before-collect
    hint (the reference's executor result-size flag analog)."""
    import logging
    from dpark_tpu import conf
    old = conf.EGEST_WARN_BYTES
    conf.EGEST_WARN_BYTES = 64                # trip at toy size
    try:
        with caplog.at_level(logging.WARNING):
            out = dict(tctx.parallelize([(i % 5, i) for i in range(100)],
                                        8)
                       .reduceByKey(lambda a, b: a + b, 8).collect())
        assert len(out) == 5
        assert any("reduce" in r.message and "collect" in r.message
                   for r in caplog.records)
    finally:
        conf.EGEST_WARN_BYTES = old


def _stage_kinds(tctx):
    """{rdd_name: kind} for the LAST job's stages."""
    rec = tctx.scheduler.history[-1]
    return {s["rdd"]: s.get("kind") for s in rec["stage_info"]}


def test_union_of_shuffles_rides_device(tctx):
    """A union of reduceByKey outputs feeding another reduceByKey (the
    windowed-stream shape, BASELINE config #4) runs the UNION stage on
    the array path: branches materialize as device batches, concatenate
    on device, and the shuffle write rides the mesh."""
    import operator
    rows = [(i % 50, i % 7) for i in range(5000)]
    b1 = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8)
    b2 = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8)
    got = dict(b1.union(b2).reduceByKey(operator.add, 8).collect())
    exp = {}
    for k, v in rows + rows:
        exp[k] = exp.get(k, 0) + v
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("UnionRDD") == "array", kinds


def test_union_mixed_ingest_and_shuffle_branches(tctx):
    """Union branches may mix raw parallelize input with reduced HBM
    shuffles (cold-start window shape); narrow ops on a branch apply
    before the concat."""
    import operator
    rows = [(i % 50, 1) for i in range(4000)]
    reduced = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8) \
        .mapValue(lambda v: v * 10)
    raw = tctx.parallelize(rows, 8)
    got = dict(raw.union(reduced).reduceByKey(operator.add, 8)
               .collect())
    exp = {}
    for k, v in rows:
        exp[k] = exp.get(k, 0) + v
    exp = {k: v + v * 10 for k, v in exp.items()}
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert kinds.get("UnionRDD") == "array", kinds


def test_union_result_stage_stays_host(tctx):
    """collect() directly over a union (result stage) keeps the object
    path — result tasks index the union's own partition layout."""
    import operator
    rows = [(i % 20, 1) for i in range(800)]
    b1 = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8)
    b2 = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8)
    got = sorted(b1.union(b2).collect())
    exp = {}
    for k, v in rows:
        exp[k] = exp.get(k, 0) + v
    assert got == sorted(list(exp.items()) * 2)
    kinds = _stage_kinds(tctx)
    assert kinds.get("UnionRDD") != "array", kinds


def test_reslice_wrong_slice_count_rides_device(tctx):
    """parallelize with numSlices != mesh width feeding a shuffle write
    re-slices host-side onto the mesh instead of declining the array
    path (the DStream queue-batch shape)."""
    import operator
    rows = [(i % 64, i % 5) for i in range(6000)]
    for nsl in (2, 3, 16):
        r = tctx.parallelize(rows, nsl).reduceByKey(operator.add, 8)
        got = dict(r.collect())
        exp = {}
        for k, v in rows:
            exp[k] = exp.get(k, 0) + v
        assert got == exp, nsl
        kinds = _stage_kinds(tctx)
        assert kinds.get("ParallelCollection") == "array", (nsl, kinds)


def test_union_shuffle_feeds_object_consumer(tctx):
    """An OBJECT-path stage consuming a union-written shuffle fetches
    through the single_map export (device rows don't correspond to the
    union's 2x map partitions; without the flag every fetch failed and
    the scheduler resubmitted the parent forever)."""
    import operator
    rows = [(i % 30, 1) for i in range(3000)]
    b1 = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8)
    b2 = tctx.parallelize(rows, 8).reduceByKey(operator.add, 8)
    u = b1.union(b2).reduceByKey(operator.add, 8)
    # str() is untraceable -> this stage runs object tasks that FETCH
    # the union's map outputs through the host bridge
    got = dict(u.map(lambda kv: (kv[0], str(kv[1]))).collect())
    exp = {}
    for k, v in rows:
        exp[k] = exp.get(k, 0) + v
    assert got == {k: str(v * 2) for k, v in exp.items()}


def test_resliced_shuffle_feeds_object_consumer(tctx):
    """Same single_map guarantee for resliced ingest: 2 logical map
    partitions redistributed over 8 devices, consumed by object tasks."""
    import operator
    rows = [(i % 40, i % 3) for i in range(4000)]
    r = tctx.parallelize(rows, 2).reduceByKey(operator.add, 8)
    got = dict(r.map(lambda kv: (kv[0], str(kv[1]))).collect())
    exp = {}
    for k, v in rows:
        exp[k] = exp.get(k, 0) + v
    assert got == {k: str(v) for k, v in exp.items()}


def test_join_source_pipeline_rides_device(tctx):
    """a.join(b) feeding further ops + a shuffle write runs the join
    as an array-path SOURCE (device expansion, no host rows): the
    TPC-H-shaped join->map->reduce pipeline is all-array."""
    import operator
    fact = [(i % 50, i % 7) for i in range(20000)]
    dim = [(i, i * 3) for i in range(50)]
    a = tctx.parallelize(fact, 8)
    b = tctx.parallelize(dim, 8)
    got = dict(a.join(b, 8)
               .map(lambda kv: (kv[0], kv[1][0] * kv[1][1]))
               .reduceByKey(operator.add, 8).collect())
    exp = {}
    for k, v in fact:
        exp[k] = exp.get(k, 0) + v * (k * 3)
    assert got == exp
    kinds = _stage_kinds(tctx)
    assert set(kinds.values()) == {"array"}, kinds
    assert "MappedRDD" in kinds, kinds    # the join-source stage's top


def test_count_answers_from_device_counts(tctx):
    """count() over an array result stage reads only counts (no row
    egest — note kind 'array+counts') and still matches the object
    path exactly; groupByKey counts KEYS via the on-device distinct
    scan over its key-sorted rows."""
    import operator
    rows = [(i % 100, i % 7) for i in range(30000)]
    assert tctx.parallelize(rows, 8).filter(
        lambda kv: kv[0] < 10).count() == 3000
    assert _stage_kinds(tctx).get("FilteredRDD") == "array+counts"
    assert tctx.parallelize(rows, 8).reduceByKey(
        operator.add, 8).count() == 100
    assert _stage_kinds(tctx).get("ShuffledRDD") == "array+counts"
    assert tctx.parallelize(rows, 8).groupByKey(8).count() == 100
    kinds = _stage_kinds(tctx)
    assert "array+counts" in kinds.values(), kinds
    # distinct-scan edge: every key unique, and a single-key skew
    assert tctx.parallelize(
        [(i, 1) for i in range(5000)], 8).groupByKey(8).count() == 5000
    assert tctx.parallelize(
        [(7, i) for i in range(5000)], 8).groupByKey(8).count() == 1


def test_reduce_monoid_answers_on_device(tctx):
    """reduce() with a provable monoid egests ndev scalars (note kind
    'array+reduced'), matching the object path exactly for ints; an
    unprovable reduce keeps the egest + host fold."""
    import operator
    vals = [((i * 7919) % 1000) - 500 for i in range(10000)]
    r = tctx.parallelize(vals, 8).map(lambda x: x * 3)
    assert r.reduce(operator.add) == sum(v * 3 for v in vals)
    assert _stage_kinds(tctx).get("MappedRDD") == "array+reduced"
    assert r.reduce(lambda a, b: a if a < b else b) \
        == min(v * 3 for v in vals)
    assert r.reduce(lambda a, b: a if a > b else b) \
        == max(v * 3 for v in vals)
    # subtraction is not a monoid: must NOT take the reduced path,
    # and must still fold in partition order like the object path
    got = tctx.parallelize([10, 1, 2, 3], 2).reduce(operator.sub)
    assert got == (10 - 1) - (2 - 3)
    assert _stage_kinds(tctx).get("ParallelCollection") \
        != "array+reduced"


def test_reduce_monoid_edge_semantics(tctx):
    """Integer-overflow, bool, and int-mul reduces keep the exact host
    fold (Python big ints) instead of wrapping on device (r4 review)."""
    import operator
    # sum would exceed int64: exact big-int answer required
    big = [2 ** 62, 2 ** 62, 2 ** 62]
    assert tctx.parallelize(big, 8).reduce(operator.add) == 3 * 2 ** 62
    # integer product overflows int64 almost immediately
    assert tctx.parallelize(list(range(1, 30)), 8) \
        .reduce(operator.mul) == __import__("math").factorial(29)
    # bool min/max must not crash the stage
    assert tctx.parallelize([True, False, True], 8).reduce(min) is False
    # float add stays on device (documented ordering divergence)
    vals = [0.5 * i for i in range(1000)]
    got = tctx.parallelize(vals, 8).map(lambda x: x + 0.25) \
        .reduce(operator.add)
    assert abs(got - sum(v + 0.25 for v in vals)) < 1e-6
    # empty devices (identity min/max) must not poison the overflow
    # bound into a needless fallback
    few = tctx.parallelize(list(range(16)), 8).filter(lambda x: x < 2)
    assert few.reduce(operator.add) == 1
    assert _stage_kinds(tctx).get("FilteredRDD") == "array+reduced"
