"""Adaptive execution (ISSUE 7): persistent stats store + cost-model
planner.

The suite proves the four decision points close their loops — wave
budget seeding, device-vs-object path pricing, skew-widened reduce
sides, map-side-combine pricing — and that the CI-safe default
(DPARK_ADAPT=observe) is BIT-IDENTICAL to off: observations are
recorded but no plan ever changes.  Device tests run on a 2-device
sliced mesh ("tpu:2") so the suite works on small containers (see the
`mesh` marker note in conftest)."""

import json
import os

import numpy as np
import pytest

from dpark_tpu import Columns, adapt, conf


@pytest.fixture(autouse=True)
def _fresh_adapt(tmp_path):
    """Every test gets its own store dir and a reset in-memory plane;
    conf knobs the tests touch are restored."""
    old = (conf.STREAM_CHUNK_ROWS, conf.EMULATED_WAVE_OOM_ROWS,
           conf._hbm_bytes_limit, conf._STREAM_CHUNK_ROWS_FALLBACK,
           conf.GROUP_AGG_REWRITE)
    adapt.configure(mode="observe", store_dir=str(tmp_path / "adapt"))
    yield
    (conf.STREAM_CHUNK_ROWS, conf.EMULATED_WAVE_OOM_ROWS,
     conf._hbm_bytes_limit, conf._STREAM_CHUNK_ROWS_FALLBACK,
     conf.GROUP_AGG_REWRITE) = old
    adapt.configure()          # back to conf-driven mode/dir


@pytest.fixture()
def tctx2():
    from dpark_tpu import DparkContext
    c = DparkContext("tpu:2")
    c.start()
    yield c
    c.stop()


# ---------------------------------------------------------------------------
# the store: framing, round-trip, corruption, concurrency format
# ---------------------------------------------------------------------------

def test_mode_grammar():
    adapt.configure(mode="on")
    assert adapt.mode() == "on" and adapt.steering()
    adapt.configure(mode="observe")
    assert adapt.enabled() and not adapt.steering()
    adapt.configure(mode="off")
    assert not adapt.enabled()
    with pytest.raises(ValueError):
        adapt.configure(mode="sometimes")


def test_store_round_trip(tmp_path):
    store = str(tmp_path / "s1")
    adapt.configure(mode="observe", store_dir=store)
    adapt.record_wave_budget(16, 4096, ok=True)
    adapt.record_wave_budget(16, 8192, ok=False)
    adapt.observe_path(("prog", "r16"), "device", 120.0)
    adapt.observe_path(("prog", "r16"), "host", 80.0)
    adapt.record_skew("site:1", rows=1000, groups=10, max_group=800,
                      parts=2)
    adapt.record_combine_ratio("site:1", rows_in=1000, rows_out=950)
    path = adapt._store_path()
    assert os.path.exists(path)
    # a fresh process (simulated by configure) reloads the same state
    adapt.configure(mode="observe", store_dir=store)
    hist = adapt.stage_history()
    assert hist["prog|r16"]["device_ms"] == 120.0
    assert hist["prog|r16"]["host_ms"] == 80.0
    with adapt._lock:
        wb = dict(adapt._agg["wave_budget"]["rb16"])
        skew = dict(adapt._agg["skew"]["site:1"])
        ratio = adapt._agg["combine"]["site:1"]["ratio"]
    assert wb == {"good": 4096, "bad": 8192}
    assert skew["max_group"] == 800 and skew["rows"] == 1000
    assert ratio == pytest.approx(0.95)


def test_store_lines_are_crc_framed(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "s2"))
    adapt.record_wave_budget(16, 4096, ok=True)
    line = open(adapt._store_path(), "rb").read().splitlines()[0]
    head, _, payload = line.partition(b" ")
    assert int(head, 16) == adapt._crc(payload)
    json.loads(payload)        # the payload itself is plain JSON


def test_corrupt_and_truncated_lines_skipped(tmp_path):
    store = str(tmp_path / "s3")
    adapt.configure(mode="observe", store_dir=store)
    adapt.record_wave_budget(16, 4096, ok=True)
    adapt.observe_path(("prog", "r16"), "device", 50.0)
    raw = open(adapt._store_path(), "rb").read()
    lines = raw.splitlines()
    # corrupt line 0's payload (crc now mismatches), truncate line 1,
    # and add plain garbage — the good line we append after must
    # still load, and nothing raises
    garbled = lines[0][:-3] + b"zzz"
    with open(adapt._store_path(), "wb") as f:
        f.write(garbled + b"\n" + lines[1][:10] + b"\nnot a line\n")
    adapt.record_skew("site:x", rows=10, groups=2, max_group=8, parts=2)
    adapt.configure(mode="observe", store_dir=store)
    adapt._ensure_loaded()
    with adapt._lock:
        skipped = adapt._counters["skipped_lines"]
        assert "rb16" not in adapt._agg["wave_budget"]
        assert adapt._agg["skew"]["site:x"]["rows"] == 10
    assert skipped == 3
    assert adapt.stage_history() == {}


def test_reset_store(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "s4"))
    adapt.record_wave_budget(16, 4096, ok=True)
    assert os.path.exists(adapt._store_path())
    adapt.reset_store()
    assert not os.path.exists(adapt._store_path())
    with adapt._lock:
        assert not adapt._agg["wave_budget"]


def test_off_mode_never_touches_disk(tmp_path):
    store = str(tmp_path / "s5")
    adapt.configure(mode="off", store_dir=store)
    adapt.record_wave_budget(16, 4096, ok=True)
    adapt.observe_path(("prog", "r16"), "device", 50.0)
    adapt.record_skew("s", rows=10, groups=2, max_group=8, parts=2)
    adapt.record_combine_ratio("s", rows_in=10, rows_out=10)
    assert not os.path.exists(store)


def test_identical_wave_budget_outcomes_deduplicate(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "s6"))
    for _ in range(5):
        adapt.record_wave_budget(16, 4096, ok=True)
    assert len(open(adapt._store_path(), "rb").read().splitlines()) == 1


def test_store_compacts_past_size_cap(tmp_path):
    """An over-cap store rewrites as its folded aggregates at load —
    the append-only file stays bounded and the state survives."""
    store = str(tmp_path / "s7")
    adapt.configure(mode="observe", store_dir=store)
    adapt.record_wave_budget(16, 4096, ok=True)
    for i in range(200):
        adapt.observe_path(("prog", "r16"), "device", 100.0 + i)
    big = os.path.getsize(adapt._store_path())
    old_cap = conf.ADAPT_STORE_MAX_BYTES
    conf.ADAPT_STORE_MAX_BYTES = big // 2
    try:
        adapt.configure(mode="observe", store_dir=store)  # reload
        adapt._ensure_loaded()
    finally:
        conf.ADAPT_STORE_MAX_BYTES = old_cap
    assert os.path.getsize(adapt._store_path()) < big // 4
    # the compacted store round-trips the folded state
    adapt.configure(mode="observe", store_dir=store)
    assert adapt.steer_wave_budget(8192, 16) == 8192  # observe: inert
    hist = adapt.stage_history()
    assert hist["prog|r16"]["device_ms"] == pytest.approx(299.0, abs=2)
    with adapt._lock:
        assert adapt._agg["wave_budget"]["rb16"]["good"] == 4096


def test_repeat_steer_logged_per_job(tmp_path, ctx):
    """A job that takes the same steered choice as its predecessor
    still logs it: record["adapt"] deltas must not silently undercount
    repeat steering (begin_job resets the de-dup epoch)."""
    adapt.configure(mode="on", store_dir=str(tmp_path / "s8"))
    adapt.record_skew("site:r", rows=1000, groups=10, max_group=900,
                      parts=2)
    for _ in range(2):
        base = adapt.begin_job()          # what _new_job_record calls
        assert adapt.suggest_partitions("site:r", 2) == 4
        ds = adapt.decisions_since(base)
        assert len(ds) == 1 and ds[0]["applied"], ds


def test_stable_key_strips_addresses():
    f1 = lambda x: x + 1          # noqa: E731
    f2 = lambda x: x + 1          # noqa: E731
    f3 = lambda x: x + 2          # noqa: E731
    assert adapt.stable_key(("k", f1)) == adapt.stable_key(("k", f2))
    assert adapt.stable_key(("k", f1)) != adapt.stable_key(("k", f3))
    class Opaque:                  # repr embeds "at 0x..."
        pass
    a, b = Opaque(), Opaque()
    assert adapt.stable_key(a) == adapt.stable_key(b)


# ---------------------------------------------------------------------------
# decision point 1: wave budget seeding
# ---------------------------------------------------------------------------

def test_steer_wave_budget_prefers_known_good(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "w1"))
    adapt.record_wave_budget(16, 2048, ok=True)
    assert adapt.steer_wave_budget(8192, 16) == 2048
    # a learned budget LARGER than the derived base never applies
    assert adapt.steer_wave_budget(1024, 16) == 1024
    # a different row-width class has no history
    assert adapt.steer_wave_budget(8192, 32) == 8192


def test_steer_wave_budget_halves_below_failed_rung(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "w2"))
    adapt.record_wave_budget(16, 4096, ok=False)
    assert adapt.steer_wave_budget(8192, 16) == 2048


def test_steer_wave_budget_inert_outside_on(tmp_path):
    for m in ("off", "observe"):
        adapt.configure(mode=m, store_dir=str(tmp_path / ("w3" + m)))
        if m == "observe":
            adapt.record_wave_budget(16, 2048, ok=True)
        assert adapt.steer_wave_budget(8192, 16) == 8192


def test_stream_chunk_rows_consults_store(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "w4"))
    conf.STREAM_CHUNK_ROWS = "auto"
    conf._hbm_bytes_limit = lambda: 0
    conf._STREAM_CHUNK_ROWS_FALLBACK = 8192
    assert conf.stream_chunk_rows(16) == 8192
    adapt.record_wave_budget(16, 1024, ok=True)
    assert conf.stream_chunk_rows(16) == 1024
    # a user-pinned budget always bypasses the store
    conf.STREAM_CHUNK_ROWS = 555
    assert conf.stream_chunk_rows(16) == 555


# ---------------------------------------------------------------------------
# decision point 2: device vs object path by predicted cost
# ---------------------------------------------------------------------------

def _seed_stage(sig, device_ms, host_ms):
    adapt.observe_path(sig, "device", device_ms)
    adapt.observe_path(sig, "host", host_ms)


def test_choose_path_needs_both_sides(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "p1"))
    sig = ("prog", "r16")
    assert adapt.choose_path(sig) is None          # no history
    adapt.observe_path(sig, "device", 100.0)
    assert adapt.choose_path(sig) is None          # device only


def test_choose_path_picks_cheaper_recorded_path(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "p2"))
    _seed_stage(("prog", "r16"), device_ms=100.0, host_ms=10.0)
    d = adapt.choose_path(("prog", "r16"))
    assert d["choice"] == "object" and d["applied"]
    assert "cheaper" in d["reason"]
    # ties (and anything inside the margin) keep the device
    _seed_stage(("prog2", "r16"), device_ms=100.0, host_ms=95.0)
    d2 = adapt.choose_path(("prog2", "r16"))
    assert d2["choice"] == "device"


def test_choose_path_observe_logs_but_returns_none(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "p3"))
    _seed_stage(("prog", "r16"), device_ms=100.0, host_ms=10.0)
    assert adapt.choose_path(("prog", "r16")) is None
    ds = adapt.summary()["decisions"]
    assert ds and ds[-1]["point"] == "path" \
        and ds[-1]["choice"] == "object" and not ds[-1]["applied"]


def test_steered_object_path_end_to_end(tmp_path, tctx2):
    """Seed a synthetic history where the host is recorded far cheaper
    for this exact program class: the next run of the same job must
    take the object path with an adapt_reason, bit-identical."""
    adapt.configure(mode="on", store_dir=str(tmp_path / "p4"))
    i = np.arange(4000, dtype=np.int64)
    data = Columns(i % 97, i % 11)

    def job():
        return sorted(tctx2.parallelize(data, 2)
                      .reduceByKey(lambda a, b: a + b, 2).collect())

    want = job()                               # runs the device path
    hist = adapt.stage_history()
    assert hist, "device run recorded no stage observations"
    kinds1 = {s["id"]: s.get("kind")
              for s in tctx2.scheduler.history[-1]["stage_info"]}
    assert "array" in kinds1.values()
    for key in hist:
        sig = tuple(key.split("|", 1))
        for _ in range(3):                     # EMA-converge the price
            adapt.observe_path(sig, "host", 0.01)
    got = job()
    assert got == want
    rec = tctx2.scheduler.history[-1]
    reasons = [s.get("adapt_reason") for s in rec["stage_info"]]
    assert any(r and "object path predicted cheaper" in r
               for r in reasons), rec["stage_info"]
    assert all(s.get("kind") != "array" for s in rec["stage_info"])
    # the job record carries the applied decisions
    assert any(d["applied"] and d["point"] == "path"
               for d in rec["adapt"]["decisions"])


# ---------------------------------------------------------------------------
# decision point 3: partition count re-planned on observed skew
# ---------------------------------------------------------------------------

def test_suggest_partitions_widens_on_dominant_group(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "k1"))
    adapt.record_skew("site:1", rows=1000, groups=10, max_group=800,
                      parts=2)
    assert adapt.suggest_partitions("site:1", 2) == 4
    # balanced histogram: the default stands
    adapt.record_skew("site:2", rows=1000, groups=10, max_group=120,
                      parts=2)
    assert adapt.suggest_partitions("site:2", 2) == 2


def test_suggest_partitions_observe_never_widens(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "k2"))
    adapt.record_skew("site:1", rows=1000, groups=10, max_group=900,
                      parts=2)
    assert adapt.suggest_partitions("site:1", 2) == 2
    ds = adapt.summary()["decisions"]
    assert ds and ds[-1]["point"] == "partitions" \
        and not ds[-1]["applied"]


def test_seg_path_records_skew_histogram(tmp_path, tctx2):
    """The device segment path's bucket histogram — computed anyway
    for the apply layout — lands in the store keyed by the grouping
    call site."""
    conf.GROUP_AGG_REWRITE = False
    adapt.configure(mode="observe", store_dir=str(tmp_path / "k3"))
    rows = [(i % 7, i % 13) for i in range(4000)]
    f = lambda vs: sum(v * v for v in vs)           # noqa: E731
    got = dict(tctx2.parallelize(rows, 2).groupByKey(2)
               .mapValue(f).collect())
    want = {}
    for k, v in rows:
        want[k] = want.get(k, 0) + v * v
    assert got == want
    rec = tctx2.scheduler.history[-1]
    assert any(s.get("kind") == "array" for s in rec["stage_info"])
    with adapt._lock:
        skews = dict(adapt._agg["skew"])
    assert skews, "seg path recorded no skew observation"
    (site, ent), = list(skews.items())[:1]
    assert "test_adapt.py" in site
    assert ent["rows"] == 4000 and ent["groups"] == 7


# ---------------------------------------------------------------------------
# decision point 4: map-side combine priced from the combine ratio
# ---------------------------------------------------------------------------

def test_map_side_combine_priced_off_at_high_ratio(tmp_path):
    adapt.configure(mode="on", store_dir=str(tmp_path / "c1"))
    assert adapt.map_side_combine("site:1", "sum")     # no history
    adapt.record_combine_ratio("site:1", rows_in=1000, rows_out=950)
    assert not adapt.map_side_combine("site:1", "sum")
    adapt.record_combine_ratio("site:2", rows_in=1000, rows_out=20)
    assert adapt.map_side_combine("site:2", "sum")


def test_map_side_combine_observe_keeps_static_default(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "c2"))
    adapt.record_combine_ratio("site:1", rows_in=1000, rows_out=990)
    assert adapt.map_side_combine("site:1", "sum")
    ds = adapt.summary()["decisions"]
    assert ds and ds[-1]["point"] == "map_combine" \
        and not ds[-1]["applied"]


def test_combining_shuffle_records_ratio(tmp_path, tctx2):
    """A device combining shuffle write knows rows in (the columnar
    source) and rows out (the stored per-partition counts): the ratio
    lands in the store keyed by the combineByKey call site."""
    adapt.configure(mode="observe", store_dir=str(tmp_path / "c3"))
    i = np.arange(6000, dtype=np.int64)
    data = Columns(i % 50, i % 7)
    n = tctx2.parallelize(data, 2) \
             .reduceByKey(lambda a, b: a + b, 2).count()
    assert n == 50
    with adapt._lock:
        ratios = {k: v["ratio"] for k, v in adapt._agg["combine"].items()}
    assert ratios, "combining shuffle recorded no ratio"
    (site, ratio), = list(ratios.items())[:1]
    assert "test_adapt.py" in site
    # 50 distinct keys; the combined rows may count per device slice
    # (each device pre-aggregates its own slice before the exchange)
    assert 50 / 6000 <= ratio <= 2 * 50 / 6000 + 1e-9, ratio


def test_group_agg_rewrite_declined_by_price(tmp_path, ctx):
    """The PR-1 linter's `group-agg` advisory as an optimizer choice:
    with a recorded all-distinct combine ratio the rewrite is declined
    (the grouped chain runs raw), and the answer does not change."""
    adapt.configure(mode="on", store_dir=str(tmp_path / "c4"))
    rows = [(i % 5, i) for i in range(100)]

    def job():
        return dict(ctx.parallelize(rows, 4).groupByKey(4)
                    .mapValue(sum).collect())

    grouped = ctx.parallelize(rows, 4).groupByKey(4)
    site = grouped.dep.adapt_site
    assert site and "test_adapt.py" in site
    assert grouped._group_agg_rewrite(sum) is not None
    want = job()
    adapt.record_combine_ratio(site, rows_in=100, rows_out=98)
    grouped2 = ctx.parallelize(rows, 4).groupByKey(4)
    # same call line -> same site key
    assert grouped2.dep.adapt_site != site or \
        grouped2._group_agg_rewrite(sum) is None
    assert job() == want


# ---------------------------------------------------------------------------
# observe-mode bit-parity with off (the acceptance gate)
# ---------------------------------------------------------------------------

def _parity_jobs(c):
    rows = [(i % 13, (i * 7) % 29) for i in range(2000)]
    r = c.parallelize(rows, 4)
    out = [sorted(r.reduceByKey(lambda a, b: a + b, 3).collect()),
           sorted((k, sorted(v)) for k, v in
                  r.groupByKey(3).collect()),
           r.map(lambda kv: kv[1]).reduce(lambda a, b: a + b)]
    j = sorted(r.join(c.parallelize(rows[::7], 2), 3).collect())
    return out + [j]


@pytest.mark.parametrize("master", ["local", "tpu:2"])
def test_observe_bit_parity_with_off(tmp_path, master):
    from dpark_tpu import DparkContext
    results = {}
    for m in ("off", "observe"):
        adapt.configure(mode=m, store_dir=str(tmp_path / ("par" + m)))
        c = DparkContext(master)
        c.start()
        try:
            results[m] = _parity_jobs(c)
            rec = c.scheduler.history[-1]
        finally:
            c.stop()
        if m == "off":
            assert "adapt" not in rec
    assert results["off"] == results["observe"]


def test_observe_bit_parity_under_faults(tmp_path):
    """Observe mode is bit-identical to off ACROSS THE CHAOS MATRIX:
    an injected fetch fault recovers identically either way."""
    from dpark_tpu import DparkContext, faults
    results = {}
    for m in ("off", "observe"):
        adapt.configure(mode=m, store_dir=str(tmp_path / ("f" + m)))
        # bounded injection (times=) like the chaos suite's
        # probabilistic tests: unbounded p= on the cogroup's
        # multi-parent fetches can exceed the recovery caps
        faults.configure("shuffle.fetch:p=0.2,seed=7,times=4")
        try:
            c = DparkContext("local")
            c.start()
            try:
                results[m] = _parity_jobs(c)
                rec = c.scheduler.history[-1]
                assert rec.get("state") == "done"
            finally:
                c.stop()
        finally:
            faults.configure(None)
    assert results["off"] == results["observe"]


# ---------------------------------------------------------------------------
# the OOM ladder feeds the store; run 2 skips the ladder
# ---------------------------------------------------------------------------

def _streamed_setup(base):
    conf._hbm_bytes_limit = lambda: 0
    conf._STREAM_CHUNK_ROWS_FALLBACK = base
    conf.STREAM_CHUNK_ROWS = "auto"


def _ladder_retries(sched, jobs0):
    """Ladder walks counted from the per-stage job records since
    history index jobs0 — degrade_reasons() de-duplicates identical
    strings across history, which would hide a warm run re-walking
    the ladder with the same budget numbers."""
    return [st["degrade_reason"]
            for rec in sched.history[jobs0:]
            for st in rec.get("stage_info", ())
            if "wave budget" in (st.get("degrade_reason") or "")]


def test_second_run_skips_oom_ladder(tmp_path, tctx2):
    """Run 1 OOMs at the derived budget, halves, succeeds, and
    persists the working rung; run 2 seeds from the store and streams
    first try — the ISSUE 7 acceptance loop."""
    adapt.configure(mode="on", store_dir=str(tmp_path / "oom1"))
    base = 1 << 13
    _streamed_setup(base)
    conf.EMULATED_WAVE_OOM_ROWS = base * 3 // 4
    ndev = tctx2.scheduler.executor.ndev
    n = base * 3 // 2 * ndev
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 1000, i & 0xFFFF)

    def run():
        jobs0 = len(tctx2.scheduler.history)
        ns = tctx2.parallelize(data, ndev) \
                  .sortByKey(numSplits=ndev).count()
        assert ns == n
        return _ladder_retries(tctx2.scheduler, jobs0)

    assert len(run()) >= 1                    # cold: walked the ladder
    assert run() == []                        # warm: seeded, no ladder
    ds = [d for d in adapt.summary()["decisions"]
          if d["point"] == "wave_budget" and d["applied"]]
    assert ds and ds[-1]["choice"] == base // 2


def test_ladder_records_even_on_object_fallback(tmp_path, tctx2):
    """Satellite: a ceiling below HALF the derived budget fails both
    ladder rungs and the stage falls back to the object path — but the
    failing rungs are persisted, so run 2 starts BELOW them and
    streams instead of re-OOMing."""
    adapt.configure(mode="on", store_dir=str(tmp_path / "oom2"))
    base = 1 << 13
    _streamed_setup(base)
    conf.EMULATED_WAVE_OOM_ROWS = base // 4       # halved rung OOMs too
    ndev = tctx2.scheduler.executor.ndev
    n = base * 3 // 2 * ndev
    i = np.arange(n, dtype=np.int64)
    data = Columns((i * 2654435761) % 1000, i & 0xFFFF)

    def run():
        jobs0 = len(tctx2.scheduler.history)
        ns = tctx2.parallelize(data, ndev) \
                  .sortByKey(numSplits=ndev).count()
        assert ns == n
        return (_ladder_retries(tctx2.scheduler, jobs0),
                tctx2.scheduler.history[-1])

    ladder1, rec1 = run()
    assert ladder1, "cold run never hit the ladder"
    assert any("object path" in (s.get("degrade_reason") or "")
               for s in rec1["stage_info"])
    with adapt._lock:
        ent = dict(adapt._agg["wave_budget"]["rb16"])
    assert ent["bad"] == base // 2 and ent["good"] is None
    ladder2, rec2 = run()
    assert ladder2 == [], ladder2             # seeded at bad//2: fits
    assert all("object path" not in (s.get("degrade_reason") or "")
               for s in rec2["stage_info"])


def test_store_persists_across_processes(tmp_path, tctx2):
    """The cross-process half of the two-run proof: a store warmed in
    THIS process seeds a context whose adapt plane reloads from disk
    (configure() drops all in-memory state first)."""
    store = str(tmp_path / "xproc")
    adapt.configure(mode="on", store_dir=store)
    adapt.record_wave_budget(16, 1234, ok=True)
    adapt.configure(mode="on", store_dir=store)   # fresh plane
    with adapt._lock:
        assert not adapt._agg["wave_budget"]      # really dropped
    assert adapt.steer_wave_budget(100000, 16) == 1234


# ---------------------------------------------------------------------------
# job records, summary schema, lint rule
# ---------------------------------------------------------------------------

def test_job_record_carries_adapt_section(tmp_path, ctx):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "r1"))
    ctx.parallelize([(1, 2), (2, 3)], 2).collect()
    rec = ctx.scheduler.history[-1]
    assert rec["adapt"]["mode"] == "observe"
    assert isinstance(rec["adapt"]["decisions"], list)


def test_summary_schema(tmp_path):
    adapt.configure(mode="observe", store_dir=str(tmp_path / "r2"))
    s = adapt.summary()
    for field in ("mode", "store", "store_hits", "store_misses",
                  "steered", "recorded", "decisions"):
        assert field in s, field


def test_adapt_stale_hint_lint_rule(tmp_path, ctx):
    from dpark_tpu.analysis import lint_plan
    adapt.configure(mode="observe", store_dir=str(tmp_path / "l1"))
    i = np.arange(100, dtype=np.int64)
    r = ctx.parallelize(Columns(i, i), 2) \
           .reduceByKey(lambda a, b: a + b)

    def rules(rep):
        return {f.rule for f in rep}

    # empty store: quiet
    assert "adapt-stale-hint" not in rules(lint_plan(r))
    # a stored budget for a DIFFERENT row-width class: stale, warn
    adapt.record_wave_budget(8, 2048, ok=True)
    rep = lint_plan(r)
    assert "adapt-stale-hint" in rules(rep)
    [f] = [f for f in rep if f.rule == "adapt-stale-hint"]
    assert "16 bytes/row" in f.message
    # a matching class present: quiet again (mixed widths are fine)
    adapt.record_wave_budget(16, 2048, ok=True)
    assert "adapt-stale-hint" not in rules(lint_plan(r))
    # off mode: always quiet
    adapt.configure(mode="off", store_dir=str(tmp_path / "l1"))
    assert "adapt-stale-hint" not in rules(lint_plan(r))


# ---------------------------------------------------------------------------
# decision point 5: pane-tree split points (ISSUE 10)
# ---------------------------------------------------------------------------

def test_pane_cost_record_and_steer():
    """record_pane_cost persists per-(site, mode) EMA tick costs;
    steer_pane_mode picks the observed-cheaper strategy only in `on`
    mode and only with BOTH strategies on record."""
    adapt.configure(mode="on", store_dir=adapt.store_dir())
    site = "pane-site-1"
    # no history: static default wins either way
    assert adapt.steer_pane_mode(site, 16, True) is True
    assert adapt.steer_pane_mode(site, 16, False) is False
    adapt.record_pane_cost(site, "tree", 120.0, 16)
    # one-sided history: still static
    assert adapt.steer_pane_mode(site, 16, False) is False
    adapt.record_pane_cost(site, "flat", 40.0, 16)
    # both observed: flat is cheaper, overriding the static tree
    assert adapt.steer_pane_mode(site, 16, True) is False
    decs = [d for d in adapt.summary()["decisions"]
            if d["point"] == "pane_split"]
    assert decs and decs[-1]["choice"] == "flat" and decs[-1]["applied"]
    ent = adapt.pane_history()[site]
    assert ent["tree_ms"] == 120.0 and ent["flat_ms"] == 40.0
    assert ent["w"] == 16


def test_pane_cost_observe_mode_never_steers():
    adapt.configure(mode="observe", store_dir=adapt.store_dir())
    site = "pane-site-2"
    adapt.record_pane_cost(site, "tree", 10.0, 8)
    adapt.record_pane_cost(site, "flat", 90.0, 8)
    # observed says tree, static says flat: observe keeps static and
    # logs the would-be as applied=False
    assert adapt.steer_pane_mode(site, 8, False) is False
    decs = [d for d in adapt.summary()["decisions"]
            if d["point"] == "pane_split"]
    assert decs and decs[-1]["applied"] is False


def test_pane_cost_round_trips_store(tmp_path):
    """Pane records survive reload in a fresh process-equivalent
    (configure resets the in-memory plane)."""
    store = str(tmp_path / "pane-store")
    adapt.configure(mode="on", store_dir=store)
    adapt.record_pane_cost("s", "tree", 55.0, 32)
    adapt.record_pane_cost("s", "flat", 11.0, 32)
    adapt.configure(mode="on", store_dir=store)     # reload from disk
    assert adapt.steer_pane_mode("s", 32, True) is False


def test_pane_stream_samples_cost(monkeypatch, tmp_path):
    """An end-to-end pane stream records ONE pane-cost line (median of
    post-warmup ticks) keyed by a cross-process-stable site."""
    import operator
    from dpark_tpu import DparkContext
    from dpark_tpu.dstream import StreamingContext
    adapt.configure(mode="observe", store_dir=str(tmp_path / "ps"))
    monkeypatch.setattr(conf, "STREAM_PANES", True)
    c = DparkContext("local")
    ssc = StreamingContext(c, 1.0)
    out = []
    q = ssc.queueStream([[("k", j)] for j in range(10)])
    q.reduceByKeyAndWindow(operator.add, 4.0,
                           invFunc=operator.sub).collect_batches(out)
    ssc.ctx.start()
    for ins in ssc.input_streams:
        ins.start()
    ssc.zero_time = 1000.0
    for k in range(1, 11):
        ssc.run_batch(1000.0 + k)
    c.stop()
    hist = adapt.pane_history()
    assert len(hist) == 1
    ent = next(iter(hist.values()))
    assert ent.get("inv_ms") is not None and ent["w"] == 4


# ---------------------------------------------------------------------------
# decision points 6 + 7: per-exchange codes and mid-job re-planning
# (ISSUE 19 — straggler-adaptive coded shuffle + re-plan at the boundary)
# ---------------------------------------------------------------------------

import operator


def _colliding_keys(n, count):
    """Distinct keys that all land in ONE hash bucket of width n —
    the dominant-bucket skew the map-side combine cannot dissolve."""
    from dpark_tpu.utils.phash import portable_hash
    out = [k for k in range(100000) if portable_hash(k) % n == 0]
    assert len(out) >= count
    return out[:count]


@pytest.fixture()
def replanning(tmp_path):
    """DPARK_REPLAN on, steering adapt plane with its own store."""
    old = (conf.REPLAN, conf.REPLAN_MIN_BYTES)
    conf.REPLAN = True
    conf.REPLAN_MIN_BYTES = 64
    adapt.configure(mode="on", store_dir=str(tmp_path / "replan"))
    yield
    (conf.REPLAN, conf.REPLAN_MIN_BYTES) = old


def _assert_replanned(rec):
    assert rec.get("replans") == 1, rec
    assert rec.get("resubmits", 0) == 0, rec
    assert rec.get("recomputes", 0) == 0, rec
    reasons = [st.get("replan_reason") for st in rec["stage_info"]
               if st.get("replan_reason")]
    assert reasons and "dominant bucket" in reasons[0], rec
    assert any(st.get("rdd") == "ResplitReaderRDD"
               for st in rec["stage_info"]), rec["stage_info"]


def test_replan_skewed_reducebykey_bit_identical(ctx, replanning):
    """Decision point 7, host path: a reduceByKey whose keys all
    collide into one bucket is re-keyed through a salted re-split at
    the stage boundary — bit-identical to the un-replanned run, no
    map task recomputed, and the SECOND run pre-salts at plan time
    (the probe finds a balanced histogram, no re-split stage)."""
    keys = _colliding_keys(4, 300)
    data = [(k, 1) for k in keys] * 3

    def job(c):
        return sorted(c.parallelize(data, 4)
                      .reduceByKey(operator.add, 4).collect())

    conf.REPLAN = False
    clean = job(ctx)
    conf.REPLAN = True
    assert job(ctx) == clean
    _assert_replanned(ctx.scheduler.history[-1])
    # run 2, same call site: pre-salted, probe finds nothing
    assert job(ctx) == clean
    rec2 = ctx.scheduler.history[-1]
    assert not rec2.get("replans"), rec2
    assert rec2["stages"] == 2, rec2
    with adapt._lock:
        assert adapt._agg["replan"], "replan record must persist"


def test_replan_skewed_groupbykey_preserves_merge_order(
        ctx, replanning):
    """groupByKey builds ORDER-SENSITIVE list combiners: the re-split
    must merge each key's per-map lists in map-id order (map-id-major
    reader splits), so the grouped values come back in exactly the
    un-replanned sequence — not merely the same multiset."""
    keys = _colliding_keys(3, 60)
    data = [(keys[i % len(keys)], i) for i in range(1200)]

    def job(c):
        return sorted((k, list(vs)) for k, vs in
                      c.parallelize(data, 5).groupByKey(3).collect())

    conf.REPLAN = False
    clean = job(ctx)
    conf.REPLAN = True
    assert job(ctx) == clean
    _assert_replanned(ctx.scheduler.history[-1])


def test_replan_device_object_path_bit_identical(tctx2, replanning):
    """The tpu:2 parity cell: object-path rows (string values decline
    the array path) write file:// buckets, so the probe sees the skew
    and the re-split runs under the device master too — and the
    pre-salted second run declines the device hash kernel by NAME
    (SaltedHashPartitioner has no device spec)."""
    keys = _colliding_keys(3, 120)
    data = [(k, "v%d" % (k % 11)) for k in keys for _ in range(3)]

    def job(c):
        return sorted((k, "".join(sorted(vs))) for k, vs in
                      c.parallelize(data, 4).groupByKey(3).collect())

    conf.REPLAN = False
    clean = job(tctx2)
    conf.REPLAN = True
    assert job(tctx2) == clean
    _assert_replanned(tctx2.scheduler.history[-1])
    assert job(tctx2) == clean                  # pre-salted run
    assert not tctx2.scheduler.history[-1].get("replans")


def test_replan_skips_tight_histograms_and_tiny_exchanges(
        ctx, replanning):
    """No dominant bucket, or an exchange under REPLAN_MIN_BYTES:
    the probe declines and the job runs the planned two stages."""
    def job(c):
        return sorted(c.parallelize([(i, 1) for i in range(400)], 4)
                      .reduceByKey(operator.add, 4).collect())

    assert job(ctx) == [(i, 1) for i in range(400)]
    rec = ctx.scheduler.history[-1]
    assert not rec.get("replans"), rec
    assert rec["stages"] == 2, rec
    # a genuinely skewed but tiny exchange stays un-replanned
    conf.REPLAN_MIN_BYTES = 1 << 30
    keys = _colliding_keys(4, 200)

    def tiny(c):
        return sorted(c.parallelize([(k, 1) for k in keys], 4)
                      .reduceByKey(operator.add, 4).collect())

    assert tiny(ctx) == [(k, 1) for k in keys]
    assert not ctx.scheduler.history[-1].get("replans")


def test_observe_mode_never_steers_code_or_replan(ctx, tmp_path):
    """The plane contract across the new decision points: observe
    mode logs the would-be code escalation AND the would-be re-plan
    (applied: false) but registers no per-shuffle code, writes no
    parity, and submits no re-split stage — results and stage shapes
    bit-identical to off, with and without fault injection."""
    from dpark_tpu import coding, faults
    from dpark_tpu.health import Sketch
    old = (conf.CODE_ADAPT, conf.REPLAN, conf.REPLAN_MIN_BYTES)
    conf.CODE_ADAPT = True
    conf.REPLAN = True
    conf.REPLAN_MIN_BYTES = 64
    keys = _colliding_keys(4, 300)
    data = [(k, 1) for k in keys] * 2

    def job(c):
        return sorted(c.parallelize(data, 4)
                      .reduceByKey(operator.add, 4).collect())

    try:
        adapt.configure(mode="off")
        clean = job(ctx)
        adapt.configure(mode="observe",
                        store_dir=str(tmp_path / "observe"))
        sk = Sketch()
        for _ in range(30):
            sk.add(0.005)
        for _ in range(5):
            sk.add(0.5)
        adapt.record_site_tail("fetch.bucket:local", sk.to_dict())
        for spec in (None, "rs(4,2)"):
            coding.configure(spec)
            p0 = coding.parity_bytes()
            for _ in range(2):          # run 2 has the xch record
                assert job(ctx) == clean
                rec = ctx.scheduler.history[-1]
                assert not rec.get("replans"), rec
                assert rec["stages"] == 2, rec
                assert rec.get("resubmits", 0) == 0
            paid = coding.parity_bytes() - p0
            # parity follows the STATIC code alone in observe mode
            assert (paid > 0) == (spec is not None), (spec, paid)
            ds = [d for d in (rec.get("adapt") or {})
                  .get("decisions", ())
                  if d.get("point") in ("code", "replan")]
            assert ds, "observe mode must log would-be decisions"
            assert all(not d["applied"] for d in ds), ds
            faults.configure("shuffle.fetch:p=0.2,seed=11")
            assert job(ctx) == clean
            faults.configure(None)
    finally:
        faults.configure(None)
        coding.configure(None)
        coding.clear_shuffle_codes()
        (conf.CODE_ADAPT, conf.REPLAN, conf.REPLAN_MIN_BYTES) = old


def test_xch_records_persist_and_fold(tmp_path):
    """"xch" store records: per-peer counts accumulate, the fetch
    wall folds as an EMA, and a fresh process (simulated reload)
    reads the same profile back."""
    store = str(tmp_path / "xch")
    adapt.configure(mode="observe", store_dir=store)
    adapt.observe_exchange("j.py:1", {"hostA": {"fetches": 4}},
                           fetch_ms=100.0)
    adapt.observe_exchange("j.py:1", {"hostA": {"fetches": 2,
                                                "repair": 1}},
                           fetch_ms=50.0)
    adapt.configure(mode="observe", store_dir=store)   # reload
    prof = adapt.exchange_profiles()
    ent = prof["j.py:1"]
    assert ent["peers"]["hostA"]["fetches"] == 6
    assert ent["peers"]["hostA"]["repair"] == 1
    assert 50.0 < ent["fetch_ms"] < 100.0, ent
    assert ent["n"] == 2
