"""Chunk-server DFS backend (SURVEY.md 2.4): the file_manager registry
drives a real network filesystem — walks, crc-verified ranged reads,
and per-chunk locations that reach RDD.preferred_locations."""

import os

import pytest

from dpark_tpu.file_manager import locations, open_file, walk
from dpark_tpu.file_manager.chunkserver import ChunkServer


@pytest.fixture()
def served_tree(tmp_path):
    root = tmp_path / "dfs"
    (root / "sub").mkdir(parents=True)
    with open(root / "a.txt", "w") as f:
        for i in range(1000):
            f.write("alpha beta %d\n" % i)
    with open(root / "sub" / "b.txt", "w") as f:
        f.write("gamma delta\n" * 100)
    srv = ChunkServer(
        str(root),
        host_map=lambda path, idx: ["fakehost%d" % (idx % 3)]).start()
    yield srv, str(root)
    srv.stop()


def test_walk_and_read(served_tree):
    srv, root = served_tree
    files = dict(walk("cfs://%s/" % srv.addr))
    assert set(os.path.basename(p) for p in files) == {"a.txt", "b.txt"}
    path = [p for p in files if p.endswith("a.txt")][0]
    with open_file(path) as f:
        first = f.readline()
        assert first == b"alpha beta 0\n"
        f.seek(0)
        assert f.read(5) == b"alpha"


def test_locations_drive_preferred(served_tree, ctx):
    srv, root = served_tree
    assert locations("cfs://%s/a.txt" % srv.addr) == ["fakehost0"]
    r = ctx.textFile("cfs://%s/a.txt" % srv.addr)
    sp = r.splits[0]
    assert r.preferred_locations(sp) == ["fakehost0"]


def test_wordcount_over_chunkserver(served_tree, ctx):
    srv, root = served_tree
    got = dict(ctx.textFile("cfs://%s/" % srv.addr)
               .flatMap(lambda line: line.split())
               .map(lambda w: (w, 1))
               .reduceByKey(lambda a, b: a + b, 2).collect())
    assert got["alpha"] == 1000
    assert got["gamma"] == 100
    assert sum(got[str(i)] if str(i) in got else 0
               for i in range(1000)) == 1000


def test_crc_mismatch_detected(tmp_path):
    root = tmp_path / "dfs2"
    root.mkdir()
    with open(root / "x.txt", "w") as f:
        f.write("hello world\n")
    srv = ChunkServer(str(root), corrupt_reads=True).start()
    try:
        with pytest.raises(IOError, match="crc32c"):
            with open_file("cfs://%s/x.txt" % srv.addr) as f:
                f.read()
    finally:
        srv.stop()


def test_escape_outside_root_rejected(served_tree):
    srv, root = served_tree
    from dpark_tpu.file_manager.chunkserver import _call
    with pytest.raises(IOError):
        _call(srv.addr, ("stat", "/../etc/passwd"))


def test_read_only(served_tree):
    srv, root = served_tree
    with pytest.raises(ValueError):
        open_file("cfs://%s/a.txt" % srv.addr, "wb")
