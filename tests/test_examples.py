"""Examples double as smoke tests (reference: SURVEY.md section 4 —
examples/demo.py, wordcount, pi, pagerank, kmeans, LR)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(name, *args, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        "DPARK_PROGRESS": "0",
        "DPARK_TPU_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name), *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    p = tmp_path_factory.mktemp("corpus") / "text.txt"
    with open(p, "w") as f:
        for i in range(2000):
            f.write("alpha beta gamma alpha %d\n" % i)
    return str(p)


def test_demo_local():
    out = run_example("demo.py")
    assert "sum: 4950" in out
    assert "text round-trip: 10" in out


@pytest.mark.mesh
def test_wordcount_both_masters(corpus):
    host = run_example("wordcount.py", corpus)
    tpu = run_example("wordcount.py", corpus, "-m", "tpu")
    # top(10) tie-breaks on unspecified order; compare order-free
    assert host.splitlines()[0] == tpu.splitlines()[0]          # alpha
    assert set(host.splitlines()[1:3]) == set(tpu.splitlines()[1:3])
    assert host.splitlines()[0].split()[0] == "4000"   # alpha count


def test_wordcount_device(corpus):
    out = run_example("wordcount_device.py", corpus)
    assert out.splitlines()[0].split() == ["4000", "alpha"]


def test_pi():
    out = run_example("pi.py")
    assert "Pi is roughly 3." in out


def test_pagerank():
    out = run_example("pagerank.py")
    assert "total rank: 1.0000" in out


@pytest.mark.mesh
def test_kmeans_tpu():
    out = run_example("kmeans.py", "-m", "tpu", timeout=400)
    assert "iter 7" in out


def test_streaming():
    out = run_example("streaming_wordcount.py")
    assert "('the', 4)" in out


@pytest.mark.mesh
def test_logistic_regression_tpu():
    out = run_example("logistic_regression.py", "-m", "tpu", timeout=400)
    assert "consistency with true boundary" in out
    pct = float(out.split("boundary:")[1].strip().rstrip("%"))
    assert pct > 85.0


@pytest.mark.mesh
def test_sssp_both_masters():
    host = run_example("sssp.py")
    tpu = run_example("sssp.py", "-m", "tpu")
    assert host.strip() == tpu.strip()
    assert host.startswith("reachable: 997/1000")
