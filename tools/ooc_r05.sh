#!/bin/bash
# Round-5 re-measurement of the 10GB out-of-core runs (VERDICT r4 #2):
# wordcount + sortgroup on current code, CPU mesh, per-byte numbers vs
# the r2 anchors (2.9 / 4.9 MB/s).
set -u
cd /root/repo
OUT=.bench_ooc
mkdir -p "$OUT"
for cfg in wordcount sortgroup; do
  echo "== $cfg start $(date -u +%H:%M:%S) =="
  timeout --signal=TERM --kill-after=120 14400 \
    python benchmarks/ooc_run.py --config "$cfg" --master tpu --gb 10 \
    > "$OUT/$cfg.json" 2> "$OUT/$cfg.err"
  echo "rc=$? for $cfg at $(date -u +%H:%M:%S)"
done
echo DONE
