#!/bin/bash
# One-shot real-hardware bench capture, fired by probe_loop.sh the moment
# the chip first answers (r3 lesson: the chip answered mid-session; capture
# artifacts IMMEDIATELY, the window may close).  Never SIGKILLs python on
# the tunnel (HARDWARE_CHECKLIST) — TERM with a long grace period.
set -u
cd /root/repo
TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
OUT=BENCH_REAL_r05.md
LOGDIR=.real_capture
mkdir -p "$LOGDIR"

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "== $name ($TS) ==" >> "$LOGDIR/capture.log"
  timeout --signal=TERM --kill-after=120 "$tmo" "$@" \
    > "$LOGDIR/$name.out" 2> "$LOGDIR/$name.err"
  echo "rc=$? for $name" >> "$LOGDIR/capture.log"
}

{
  echo "# BENCH_REAL_r05 — real-chip capture at $TS"
  echo
  echo "Automatic capture fired by the probe loop on first chip contact."
  echo "Raw outputs in $LOGDIR/."
} > "$OUT"

# 1. the canonical driver bench (auto-scales when a real chip answers);
#    A/B of the _lex_sort reformulation is inside (post-fix code).
run bench 2400 python bench.py
{
  echo; echo "## bench.py"; echo '```'
  cat "$LOGDIR/bench.out"; echo '```'
} >> "$OUT"

# 2. OOC: the r3 weak spot (0.0014 GB/s real).  Post-fix wave pipeline.
#    DPARK_TPU_PLATFORM=tpu: ooc_run defaults to the emulated CPU mesh
#    otherwise — this capture exists to measure the REAL chip.
run ooc 2400 env DPARK_TPU_PLATFORM=tpu python benchmarks/ooc_run.py --config wordcount --master tpu --gb 1
{
  echo; echo "## ooc_run (1 GB wordcount)"; echo '```'
  cat "$LOGDIR/ooc.out"; echo '```'
} >> "$OUT"

# 3. Pregel PageRank (BASELINE config #4 analog on device)
run pagerank 1200 python benchmarks/pagerank_bench.py --vertices 200000
{
  echo; echo "## pagerank_bench"; echo '```'
  cat "$LOGDIR/pagerank.out"; echo '```'
} >> "$OUT"

echo "$TS capture complete" >> "$LOGDIR/capture.log"
