#!/bin/bash
# Persistent TPU probe loop (VERDICT r3 #1: "retry at intervals all round").
# Writes status to /root/repo/.probe_status.json on every attempt.
# Never SIGKILLs the probe (HARDWARE_CHECKLIST: kills can wedge the tunnel);
# uses SIGTERM with a long grace period via `timeout`.
STATUS=/root/repo/.probe_status.json
LOG=/root/repo/.probe_loop.log
while true; do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  OUT=$(timeout --signal=TERM --kill-after=60 240 python - <<'EOF' 2>&1
import json, time
t0 = time.time()
import jax
devs = jax.devices()
d = devs[0]
import jax.numpy as jnp
x = jnp.arange(1024, dtype=jnp.int32)
s = int(jnp.sum(x).block_until_ready())
assert s == 1024*1023//2
print(json.dumps({"ok": True, "platform": d.platform, "kind": getattr(d, "device_kind", "?"),
                  "n": len(devs), "probe_s": round(time.time()-t0, 2)}))
EOF
)
  RC=$?
  if [ $RC -eq 0 ] && echo "$OUT" | tail -1 | grep -q '"ok": true'; then
    LINE=$(echo "$OUT" | tail -1)
    echo "{\"ts\": \"$TS\", \"alive\": true, \"probe\": $LINE}" > "$STATUS"
    echo "$TS ALIVE $LINE" >> "$LOG"
    # first contact: capture real-hardware bench artifacts NOW (the
    # r3 chip answered mid-session and went away again)
    if [ ! -e /root/repo/.real_capture_done ]; then
      touch /root/repo/.real_capture_done
      echo "$TS CAPTURE starting" >> "$LOG"
      bash /root/repo/tools/real_capture.sh
      echo "$TS CAPTURE done" >> "$LOG"
    fi
  else
    echo "{\"ts\": \"$TS\", \"alive\": false, \"rc\": $RC}" > "$STATUS"
    echo "$TS DEAD rc=$RC" >> "$LOG"
  fi
  sleep 300
done
