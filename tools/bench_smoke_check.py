#!/usr/bin/env python
"""Bench smoke gate (ISSUE 2 satellite; extended for ISSUE 3): run
bench.py at tiny sizes on the emulated CPU mesh and assert every
emitted JSON line parses AND the out-of-core line carries the
overlapped-wave-pipeline fields (ingest/compute/exchange/spill ms,
device-idle fraction), the per-phase wall-time table (`phases`:
ingest/tokenize, narrow, exchange, spill, export), and the
`fallback_reasons` list (why any stage left the array path).  This is
a SCHEMA gate, not a performance gate — CI machines are too noisy to
grade throughput, but a refactor that silently drops the pipeline
metrics (or breaks the bench's JSON contract) fails here.

Usage: python tools/bench_smoke_check.py
Env overrides pass straight through to bench.py (BENCH_PAIRS, ...).
"""

import json
import os
import subprocess
import sys

PIPELINE_FIELDS = ("waves", "ingest_ms", "compute_ms", "exchange_ms",
                   "spill_ms", "device_idle_frac", "pipeline_depth",
                   "donated")

# per-phase wall-time table (ISSUE 3 satellite): the streamed run must
# report where its time went, phase by phase
PHASE_FIELDS = ("ingest_tokenize_ms", "narrow_ms", "exchange_ms",
                "spill_ms", "export_ms")


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # children (bench.py spawns its own grandchildren, stream_rate.py
    # runs from benchmarks/) must import dpark_tpu even when the repo
    # is not pip-installed (containers run the smoke from a checkout)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ndev = env.setdefault("BENCH_SMOKE_DEVICES", "2")
    # tiny sizes + an explicitly requested cpu mesh; the device count
    # stays small so the smoke runs on 2-CPU runners (8-device
    # collectives need ~one host CPU per device)
    env.setdefault("BENCH_PAIRS", "200000")
    env.setdefault("BENCH_KEYS", "4096")
    env.setdefault("BENCH_OOC_GB", "0.01")
    env.setdefault("BENCH_EXTRAS", "0")
    env.setdefault("BENCH_ADAPT_BASE_ROWS", "16384")
    env.setdefault("BENCH_BULK_ROWS", "250000")
    env.setdefault("BENCH_CODE_ADAPT_PAIRS", "60000")
    env.setdefault("BENCH_CODE_ADAPT_REPS", "2")
    env.setdefault("BENCH_REPLAN_KEYS", "12000")
    env.setdefault("BENCH_TABLE_ROWS", "200000")
    env.setdefault("BENCH_RECOVERY_PAIRS", "20000")
    env.setdefault("BENCH_PROBE_ATTEMPTS", "1")
    env.setdefault("BENCH_PROBE_TIMEOUT", "120")
    env.setdefault("BENCH_PLATFORM", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%s"
            % ndev).strip()
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, env=env,
        timeout=int(env.get("BENCH_SMOKE_TIMEOUT", "1500")))
    sys.stderr.write(proc.stderr[-4000:])
    print(proc.stdout)
    if proc.returncode != 0:
        print("FAIL: bench.py exited %d" % proc.returncode)
        return 1
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if not lines:
        print("FAIL: bench.py emitted no JSON lines")
        return 1
    parsed = []
    for ln in lines:
        try:
            parsed.append(json.loads(ln))
        except ValueError as e:
            print("FAIL: unparseable JSON line %r: %s" % (ln[:120], e))
            return 1
    ooc = [p for p in parsed
           if str(p.get("metric", "")).startswith("ooc_reduceByKey")]
    if not ooc:
        print("FAIL: no ooc_reduceByKey line (the streamed path did "
              "not run)")
        return 1
    pipe = ooc[0].get("pipeline")
    if not isinstance(pipe, dict):
        print("FAIL: ooc line carries no pipeline dict: %r" % ooc[0])
        return 1
    missing = [f for f in PIPELINE_FIELDS if f not in pipe]
    if missing:
        print("FAIL: pipeline dict missing %r (got %r)"
              % (missing, sorted(pipe)))
        return 1
    if not pipe["waves"] or pipe["waves"] < 2:
        print("FAIL: expected a multi-wave stream, got waves=%r"
              % (pipe["waves"],))
        return 1
    phases = ooc[0].get("phases")
    if not isinstance(phases, dict):
        print("FAIL: ooc line carries no phases dict: %r"
              % sorted(ooc[0]))
        return 1
    missing = [f for f in PHASE_FIELDS if f not in phases]
    if missing:
        print("FAIL: phases dict missing %r (got %r)"
              % (missing, sorted(phases)))
        return 1
    if "fallback_reasons" not in ooc[0] \
            or not isinstance(ooc[0]["fallback_reasons"], list):
        print("FAIL: ooc line carries no fallback_reasons list: %r"
              % sorted(ooc[0]))
        return 1
    # ISSUE 5 satellite: chaos/recovery observability must ride the
    # bench JSON — per-site fault counters (empty dict when no
    # injection) and the degrade/resubmit/retry summary with reasons
    if not isinstance(ooc[0].get("faults"), dict):
        print("FAIL: ooc line carries no faults dict: %r"
              % sorted(ooc[0]))
        return 1
    degrades = ooc[0].get("degrades")
    if not isinstance(degrades, dict) \
            or not isinstance(degrades.get("reasons"), list) \
            or "resubmits" not in degrades:
        print("FAIL: ooc line carries no degrades summary "
              "(reasons/resubmits): %r" % (degrades,))
        return 1
    # ISSUE 6: the coded-shuffle decode counters must ride the ooc
    # line (mode "off" + zero counts when no code is configured) and
    # the coded_shuffle_overhead A/B line must be present with its
    # `coding` section — the ratio itself is not graded here (CI boxes
    # are too noisy; BENCH_*.json records the honest number)
    decodes = ooc[0].get("decodes")
    if not isinstance(decodes, dict) or "mode" not in decodes:
        print("FAIL: ooc line carries no decodes section with a "
              "mode: %r" % (decodes,))
        return 1
    coded = [p for p in parsed
             if p.get("metric") == "coded_shuffle_overhead"]
    if not coded:
        print("FAIL: no coded_shuffle_overhead line")
        return 1
    cod = coded[0].get("coding")
    if not isinstance(cod, dict) or cod.get("mode") != "rs(4,2)" \
            or "value" not in coded[0]:
        print("FAIL: coded line missing value/coding section: %r"
              % coded[0])
        return 1
    for field in ("repair", "straggler_win", "decode_failures"):
        if field not in cod:
            print("FAIL: coding section missing %r (got %r)"
                  % (field, sorted(cod)))
            return 1
    if cod["decode_failures"]:
        print("FAIL: coded A/B hit decode failures with no faults "
              "injected: %r" % cod)
        return 1
    # ISSUE 12: the bulk-channel vs pickled-bridge A/B line must be
    # present with bit-parity between the two representations and the
    # bulk side actually having streamed (the ratio itself is not
    # graded here — CI boxes are too noisy; BENCH_*.json records the
    # honest number against the >=2x acceptance bar)
    bk = [p for p in parsed
          if p.get("metric") == "bulk_channel_vs_bridge"]
    if not bk:
        print("FAIL: no bulk_channel_vs_bridge line")
        return 1
    for field in ("value", "bridge_MBps", "bulk_MBps",
                  "p99_bridge_ms", "p99_bulk_ms", "parity",
                  "bulk_streams"):
        if field not in bk[0]:
            print("FAIL: bulk line missing %r (got %r)"
                  % (field, sorted(bk[0])))
            return 1
    if not bk[0]["parity"]:
        print("FAIL: bulk channel and pickled bridge disagreed on "
              "the data: %r" % bk[0])
        return 1
    if not bk[0]["bulk_streams"]:
        print("FAIL: bulk A/B never opened a bulk stream: %r" % bk[0])
        return 1
    # ISSUE 7: adaptive-execution accounting must ride the ooc line
    # (mode + store/steer counters + decision list — empty decisions
    # in the default observe mode) and the warm-vs-cold A/B line must
    # be present: the warm run seeds its wave budget from the store,
    # so it must report store hits and NO MORE ladder retries than the
    # cold run (wall itself is not graded — CI boxes are too noisy)
    ad = ooc[0].get("adapt")
    if not isinstance(ad, dict) or "mode" not in ad \
            or "store_hits" not in ad \
            or not isinstance(ad.get("decisions"), list):
        print("FAIL: ooc line carries no adapt section "
              "(mode/store_hits/decisions): %r" % (ad,))
        return 1
    # ISSUE 8: the trace section must ride the ooc line — mode + span
    # count always ({"mode": "off", "spans": 0} untraced); a traced
    # run must additionally carry the critical-path summary
    tr = ooc[0].get("trace")
    if not isinstance(tr, dict) or "mode" not in tr \
            or "spans" not in tr:
        print("FAIL: ooc line carries no trace section "
              "(mode/spans): %r" % (tr,))
        return 1
    if tr["mode"] != "off" and "critical_path" not in tr:
        print("FAIL: traced ooc run carries no critical_path "
              "summary: %r" % (tr,))
        return 1
    # ISSUE 14: the health section must ride the ooc line — mode +
    # sites dict always ({"mode": "on", "sites": {}} when untraced);
    # the overhead A/B line must be present with NONZERO site
    # sketches on its ring-traced run (the ratio itself is not graded
    # here — CI boxes are too noisy; BENCH_*.json records the honest
    # number against the <=1.03 acceptance bar)
    hl = ooc[0].get("health")
    if not isinstance(hl, dict) or "mode" not in hl \
            or not isinstance(hl.get("sites"), dict):
        print("FAIL: ooc line carries no health section "
              "(mode/sites): %r" % (hl,))
        return 1
    hb = [p for p in parsed
          if str(p.get("metric", "")).startswith(
              "health_plane_overhead")]
    if not hb:
        print("FAIL: no health_plane_overhead line")
        return 1
    for field in ("value", "t_off_s", "t_on_s", "sites"):
        if field not in hb[0]:
            print("FAIL: health line missing %r (got %r)"
                  % (field, sorted(hb[0])))
            return 1
    if not hb[0]["sites"]:
        print("FAIL: health A/B folded zero site sketches — the sink "
              "never observed the traced run: %r" % hb[0])
        return 1
    # ISSUE 15: the ledger section must ride the ooc line — mode +
    # tenants dict always ({"mode": "on", "tenants": {}} untraced);
    # the overhead A/B line must be present with NONZERO accounts and
    # the conservation check attached (the ratio itself is not graded
    # here — CI boxes are too noisy; BENCH_*.json records the honest
    # number against the <=1.03 acceptance bar)
    lg = ooc[0].get("ledger")
    if not isinstance(lg, dict) or "mode" not in lg \
            or not isinstance(lg.get("tenants"), dict):
        print("FAIL: ooc line carries no ledger section "
              "(mode/tenants): %r" % (lg,))
        return 1
    lb = [p for p in parsed
          if str(p.get("metric", "")).startswith(
              "ledger_plane_overhead")]
    if not lb:
        print("FAIL: no ledger_plane_overhead line")
        return 1
    for field in ("value", "t_off_s", "t_on_s", "accounts",
                  "conservation"):
        if field not in lb[0]:
            print("FAIL: ledger line missing %r (got %r)"
                  % (field, sorted(lb[0])))
            return 1
    if not lb[0]["accounts"]:
        print("FAIL: ledger A/B folded zero accounts — the sink "
              "never observed the traced run: %r" % lb[0])
        return 1
    lcons = lb[0]["conservation"]
    if not isinstance(lcons, dict) or "ratio" not in lcons \
            or "mesh_busy_s" not in lcons:
        print("FAIL: ledger conservation section malformed: %r"
              % (lcons,))
        return 1
    if lcons.get("ok") is False:
        print("FAIL: ledger conservation broke on the A/B: "
              "attributed %.3fs of %.3fs mesh-busy (ratio %r)"
              % (lcons["attributed_device_s"], lcons["mesh_busy_s"],
                 lcons["ratio"]))
        return 1
    # ISSUE 16: the lockcheck A/B line must be present with nonzero
    # acquisitions and an ACYCLIC observed graph (a cycle in the bench
    # run is a real ordering bug, not an overhead artifact).  The
    # ratio itself is not graded here — CI boxes are too noisy;
    # BENCH_*.json records the honest number against the <=1.03
    # acceptance bar.  Set BENCH_LOCKCHECK_MAX on a quiet box to
    # grade it strictly.
    kb = [p for p in parsed
          if str(p.get("metric", "")).startswith("lockcheck_overhead")]
    if not kb:
        print("FAIL: no lockcheck_overhead line")
        return 1
    for field in ("value", "t_off_s", "t_on_s", "acquisitions",
                  "edges", "cycles"):
        if field not in kb[0]:
            print("FAIL: lockcheck line missing %r (got %r)"
                  % (field, sorted(kb[0])))
            return 1
    if not kb[0]["acquisitions"]:
        print("FAIL: lockcheck A/B recorded zero acquisitions — the "
              "sanitizer never observed the run: %r" % kb[0])
        return 1
    if kb[0]["cycles"]:
        print("FAIL: lockcheck A/B observed a lock-order CYCLE — a "
              "real ordering bug, not an overhead artifact: %r"
              % kb[0])
        return 1
    lk_max = os.environ.get("BENCH_LOCKCHECK_MAX")
    if lk_max and kb[0]["value"] > float(lk_max):
        print("FAIL: lockcheck overhead %.3fx exceeds the %sx bar "
              "(t_off=%.4fs t_on=%.4fs)"
              % (kb[0]["value"], lk_max, kb[0]["t_off_s"],
                 kb[0]["t_on_s"]))
        return 1
    # ISSUE 20: the crash-recovery chaos certification line must be
    # present and its INVARIANTS must hold — the victim controller was
    # actually kill -9ed (exit 137, no output), the restarted
    # controller replayed >= 1 completed stage from the journal with 0
    # recomputes, the replay left its trace event, and all three runs
    # (journal-off, journal-on, post-crash resume) are bit-identical.
    # The overhead ratio itself is not graded here (CI boxes are too
    # noisy; BENCH_*.json records the honest number against the
    # <=1.02x acceptance bar).
    jr = [p for p in parsed
          if str(p.get("metric", "")).startswith("journal_recovery")]
    if not jr:
        print("FAIL: no journal_recovery line (the chaos leg did not "
              "run)")
        return 1
    for field in ("value", "parity", "victim_killed", "resumed_stages",
                  "recomputes", "replay_traced", "off", "on", "resume"):
        if field not in jr[0]:
            print("FAIL: journal_recovery line missing %r (got %r)"
                  % (field, sorted(jr[0])))
            return 1
    if not jr[0]["victim_killed"]:
        print("FAIL: the chaos victim survived its kill -9 — the "
              "certification measured nothing: %r" % jr[0])
        return 1
    if not jr[0]["parity"]:
        print("FAIL: journal-off, journal-on and post-crash resume "
              "runs disagreed on the answer: %r" % jr[0])
        return 1
    if jr[0]["resumed_stages"] < 1:
        print("FAIL: the restarted controller replayed no completed "
              "stage from the journal: %r" % jr[0])
        return 1
    if jr[0]["recomputes"]:
        print("FAIL: recovery recomputed %r surviving map partitions "
              "(expected 0 — the journal should have seeded them): %r"
              % (jr[0]["recomputes"], jr[0]))
        return 1
    if not jr[0]["replay_traced"]:
        print("FAIL: the resume run left no journal.replay trace "
              "event: %r" % jr[0])
        return 1
    aab = [p for p in parsed
           if str(p.get("metric", "")).startswith("adapt_warm_vs_cold")]
    if not aab:
        print("FAIL: no adapt_warm_vs_cold line")
        return 1
    cold, warm = aab[0].get("cold"), aab[0].get("warm")
    for side, name in ((cold, "cold"), (warm, "warm")):
        if not isinstance(side, dict) or "wall_s" not in side \
                or "ladder_retries" not in side \
                or "store_hits" not in side:
            print("FAIL: adapt A/B %s side missing "
                  "wall_s/ladder_retries/store_hits: %r" % (name, side))
            return 1
    if warm["ladder_retries"] > cold["ladder_retries"]:
        print("FAIL: warm run walked MORE of the OOM ladder than the "
              "cold run: %r" % aab[0])
        return 1
    if not warm["store_hits"]:
        print("FAIL: warm run reported no store hits: %r" % aab[0])
        return 1
    # the CI two-pass smoke (second pass against a pre-warmed
    # DPARK_ADAPT_DIR) proves CROSS-PROCESS persistence: even the
    # "cold" run seeds from the store left by pass one
    if os.environ.get("BENCH_SMOKE_EXPECT_WARM_STORE"):
        if cold["ladder_retries"] or not cold["store_hits"]:
            print("FAIL: pre-warmed store did not seed the cold run "
                  "(expected 0 ladder retries, >=1 store hit): %r"
                  % aab[0])
            return 1
    # ISSUE 19: per-exchange code re-pricing + mid-job re-plan — the
    # adaptive_code line must show the hot exchange ESCALATED, the
    # cold exchange PINNED UNCODED, and adaptive parity strictly
    # below the static rs(4,2) leg; the skew_replan line must record
    # exactly one mid-job re-plan with zero resubmits/recomputes,
    # its reason, and a pre-salted (replan-free) follow-up.  Wall
    # ratios are not graded here (CI boxes are too noisy;
    # BENCH_*.json records the honest numbers against the <=1.1x
    # adaptive and reduce-wall-improvement bars).
    ac = [p for p in parsed if p.get("metric") == "adaptive_code"]
    if not ac:
        print("FAIL: no adaptive_code line")
        return 1
    for field in ("value", "static", "adaptive", "parity_ratio",
                  "hot_escalated", "cold_pinned_uncoded"):
        if field not in ac[0]:
            print("FAIL: adaptive_code line missing %r (got %r)"
                  % (field, sorted(ac[0])))
            return 1
    if not ac[0]["hot_escalated"] or not ac[0]["cold_pinned_uncoded"]:
        print("FAIL: adaptive code policy did not steer both ways "
              "(hot_escalated=%r cold_pinned_uncoded=%r)"
              % (ac[0]["hot_escalated"], ac[0]["cold_pinned_uncoded"]))
        return 1
    if not (ac[0]["adaptive"].get("parity_bytes", 1 << 60)
            < ac[0]["static"].get("parity_bytes", 0)):
        print("FAIL: adaptive leg did not shed parity bytes vs the "
              "static code: %r vs %r"
              % (ac[0]["adaptive"], ac[0]["static"]))
        return 1
    rp = [p for p in parsed if p.get("metric") == "skew_replan"]
    if not rp:
        print("FAIL: no skew_replan line")
        return 1
    for field in ("value", "t_off_s", "t_replan_s", "t_presalt_s",
                  "reduce_off_s", "reduce_presalt_s", "replans",
                  "resubmits", "recomputes", "replan_reason",
                  "presalt_replans"):
        if field not in rp[0]:
            print("FAIL: skew_replan line missing %r (got %r)"
                  % (field, sorted(rp[0])))
            return 1
    if rp[0]["replans"] != 1 or rp[0]["resubmits"] \
            or rp[0]["recomputes"]:
        print("FAIL: skew re-plan must re-plan exactly once with "
              "zero resubmits/recomputes: %r" % rp[0])
        return 1
    if rp[0]["presalt_replans"]:
        print("FAIL: pre-salted follow-up re-planned again: %r"
              % rp[0])
        return 1
    if "dominant bucket" not in str(rp[0]["replan_reason"] or ""):
        print("FAIL: replan_reason missing the bucket histogram "
              "evidence: %r" % rp[0]["replan_reason"])
        return 1
    # ISSUE 9: the resident-service A/B line must be present — the
    # warm re-submission must show ZERO compiles with cache hits (the
    # amortized-compile acceptance), the concurrent section must be
    # bit-identical (parity), and per-job queue-wait must ride the
    # `jobs` list.  Latency/wall ratios are not graded here (CI boxes
    # are too noisy; BENCH_*.json records the honest numbers).
    sv = [p for p in parsed
          if str(p.get("metric", "")).startswith("service_warm_submit")]
    if not sv:
        print("FAIL: no service_warm_submit line")
        return 1
    for side in ("cold", "warm"):
        d = sv[0].get(side)
        if not isinstance(d, dict) or "compiles" not in d \
                or "first_wave_ms" not in d or "cache_hits" not in d:
            print("FAIL: service %s side missing compiles/"
                  "first_wave_ms/cache_hits: %r" % (side, d))
            return 1
    if sv[0]["warm"]["compiles"] != 0:
        print("FAIL: warm service submission re-compiled %d programs "
              "(expected 0): %r" % (sv[0]["warm"]["compiles"], sv[0]))
        return 1
    if not sv[0]["warm"]["cache_hits"]:
        print("FAIL: warm service submission hit the program cache 0 "
              "times: %r" % sv[0])
        return 1
    if not sv[0]["cold"]["compiles"]:
        print("FAIL: cold service submission compiled nothing — the "
              "A/B measured a pre-warmed server: %r" % sv[0])
        return 1
    conc = sv[0].get("concurrent")
    if not isinstance(conc, dict) or not conc.get("parity"):
        print("FAIL: concurrent service jobs broke parity: %r"
              % (conc,))
        return 1
    svc = sv[0].get("service")
    if not isinstance(svc, dict) \
            or not isinstance(svc.get("program_cache"), dict):
        print("FAIL: service section missing program_cache: %r"
              % (svc,))
        return 1
    jobs = sv[0].get("jobs")
    if not isinstance(jobs, list) or not jobs \
            or any("queue_wait_ms" not in j for j in jobs):
        print("FAIL: service jobs list missing queue_wait_ms: %r"
              % (jobs,))
        return 1
    # ISSUE 14: per-tenant SLO attainment must ride the service line —
    # the A/B declares a generous target, so every tenant must be
    # tracked with attainment + burn + violation counters
    slo = sv[0].get("slo")
    if not isinstance(slo, dict) or not slo:
        print("FAIL: service line carries no per-tenant slo section: "
              "%r" % (slo,))
        return 1
    # ISSUE 15: the service line must carry the per-tenant ledger with
    # BOTH named tenants attributed and the two-tenant conservation
    # check not broken (the 10% bar is graded from BENCH_*.json; here
    # only `ok is False` fails — CI boxes are too noisy to grade the
    # exact ratio)
    sled = sv[0].get("ledger")
    if not isinstance(sled, dict) \
            or not isinstance(sled.get("tenants"), dict) \
            or not isinstance(sled.get("conservation"), dict):
        print("FAIL: service line carries no ledger section "
              "(tenants/conservation): %r" % (sled,))
        return 1
    for tenant in ("tenant-a", "tenant-b"):
        t = sled["tenants"].get(tenant)
        if not isinstance(t, dict) or "device_seconds" not in t:
            print("FAIL: service ledger missing %r attribution: %r"
                  % (tenant, sled["tenants"]))
            return 1
    if not sled["tenants"]["tenant-a"].get("device_seconds"):
        print("FAIL: tenant-a (the device-bound tenant) shows zero "
              "attributed device seconds: %r" % sled["tenants"])
        return 1
    if sled["conservation"].get("ok") is False:
        print("FAIL: two-tenant conservation broke: %r"
              % sled["conservation"])
        return 1
    for tenant, t in slo.items():
        for field in ("slo_ms", "attainment", "burn",
                      "violations_total"):
            if field not in t:
                print("FAIL: tenant %r slo missing %r (got %r)"
                      % (tenant, field, sorted(t)))
                return 1
    # ISSUE 17: the AOT restart A/B line must be present — the warm
    # PROCESS (fresh interpreter against the cache dir the cold
    # process populated) must report 0 backend compiles with every
    # executable loaded off disk, and the two processes must agree on
    # the answer.  The wall ratio itself is not graded here (CI boxes
    # are too noisy; BENCH_*.json records the honest number).
    ar = [p for p in parsed
          if str(p.get("metric", "")).startswith("aot_restart")]
    if not ar:
        print("FAIL: no aot_restart line")
        return 1
    for side in ("cold", "warm"):
        d = ar[0].get(side)
        if not isinstance(d, dict) or "wall_s" not in d \
                or "backend_compiles" not in d \
                or not isinstance(d.get("aot"), dict):
            print("FAIL: aot %s side missing wall_s/backend_compiles/"
                  "aot: %r" % (side, d))
            return 1
    if not ar[0]["parity"]:
        print("FAIL: cold and warm AOT processes disagreed on the "
              "answer: %r" % ar[0])
        return 1
    if ar[0]["warm"]["backend_compiles"] != 0:
        print("FAIL: warm AOT process ran %r backend compiles "
              "(expected 0 — every executable should deserialize off "
              "disk): %r" % (ar[0]["warm"]["backend_compiles"], ar[0]))
        return 1
    if not ar[0]["cold"]["backend_compiles"]:
        print("FAIL: cold AOT process compiled nothing — the A/B "
              "measured a pre-warmed cache dir: %r" % ar[0])
        return 1
    if not ar[0]["cold"]["aot"].get("stores"):
        print("FAIL: cold AOT process stored no executables: %r"
              % ar[0])
        return 1
    if not ar[0]["warm"]["aot"].get("loads"):
        print("FAIL: warm AOT process loaded no executables off "
              "disk: %r" % ar[0])
        return 1
    # ISSUE 18: the shared-computation reuse line must be present —
    # tenant-b's identical query must be a full cache HIT (zero scan
    # chunks, bit-identical answer) with the ledger billing the hit
    # to tenant-b at ZERO device-seconds, and the partial-aggregate
    # cell must merge a cached aggregate with a residual scan
    # bit-identically.  The wall ratios are not graded here (CI boxes
    # are too noisy; BENCH_*.json records the honest numbers against
    # the >=5x acceptance bar).
    rr = [p for p in parsed
          if str(p.get("metric", "")).startswith("result_reuse")]
    if not rr:
        print("FAIL: no result_reuse line")
        return 1
    ruse = rr[0].get("reuse")
    if not isinstance(ruse, dict):
        print("FAIL: result_reuse line carries no reuse cell: %r"
              % sorted(rr[0]))
        return 1
    for field in ("t_cold_s", "t_warm_s", "speedup", "parity",
                  "scan_cold", "scan_warm", "hits", "stores",
                  "tenant_b", "tenant_a_device_s"):
        if field not in ruse:
            print("FAIL: reuse cell missing %r (got %r)"
                  % (field, sorted(ruse)))
            return 1
    if not ruse["parity"]:
        print("FAIL: cached and scanned answers disagreed: %r" % ruse)
        return 1
    if not ruse["hits"] or not ruse["stores"]:
        print("FAIL: reuse cell never hit/stored the result cache "
              "(hits=%r stores=%r)" % (ruse["hits"], ruse["stores"]))
        return 1
    if ruse["scan_warm"].get("chunks_total", 0):
        print("FAIL: the warm (cached) query still scanned %r "
              "chunks — the hit was not served from memory: %r"
              % (ruse["scan_warm"]["chunks_total"], ruse))
        return 1
    if not ruse["scan_cold"].get("chunks_total", 0):
        print("FAIL: the cold query scanned nothing — the A/B "
              "measured a pre-warmed cache: %r" % ruse)
        return 1
    tb = ruse["tenant_b"]
    if not isinstance(tb, dict) or not tb.get("resultcache_hits"):
        print("FAIL: ledger shows no resultcache hit billed to "
              "tenant-b: %r" % (tb,))
        return 1
    if tb.get("device_seconds"):
        print("FAIL: the cache-served tenant was billed %r device-"
              "seconds (expected 0 — no job ran): %r"
              % (tb["device_seconds"], tb))
        return 1
    part = rr[0].get("partial")
    if not isinstance(part, dict) or not part.get("parity"):
        print("FAIL: partial-aggregate merge broke parity with the "
              "plane-off plan: %r" % (part,))
        return 1
    if not part.get("partial_hits"):
        print("FAIL: partial cell recorded no partial-aggregate "
              "hit: %r" % part)
        return 1
    pscan = part.get("scan_reuse")
    if not isinstance(pscan, dict) \
            or not pscan.get("chunks_skipped", 0):
        print("FAIL: the residual scan skipped no chunks — the merge "
              "re-read the cached range: %r" % (pscan,))
        return 1
    # ISSUE 4 satellite: the segmented-apply A/B line must be present
    # with its schema (the ratio itself is not graded here — CI boxes
    # are too noisy — but the device side must have ridden the array
    # path, or the metric measures the fallback it exists to catch)
    gm = [p for p in parsed
          if str(p.get("metric", "")).startswith(
              "group_mapvalues_device_vs_host")]
    if not gm:
        print("FAIL: no group_mapvalues_device_vs_host line")
        return 1
    for field in ("value", "t_device_s", "t_host_s",
                  "device_rode_array_path"):
        if field not in gm[0]:
            print("FAIL: groupmap line missing %r (got %r)"
                  % (field, sorted(gm[0])))
            return 1
    if not gm[0]["device_rode_array_path"]:
        print("FAIL: groupmap device side left the array path: %r"
              % gm[0])
        return 1
    # ISSUE 13: the columnar query plane A/B must be present with
    # bit-parity between the device plan and the host row path, the
    # device side fully on the array path (no fallback_reason on any
    # stage — else the metric measures the very fallback it exists to
    # catch), and the scan PRUNED (fewer columns read than the table
    # has; the query references 4 of 5).  The ratio itself is not
    # graded here (CI boxes are too noisy; BENCH_*.json records the
    # honest number against the >=3x acceptance bar).
    tq = [p for p in parsed
          if str(p.get("metric", "")).startswith(
              "table_query_device_vs_host")]
    if not tq:
        print("FAIL: no table_query_device_vs_host line")
        return 1
    for field in ("value", "t_device_s", "t_host_s", "parity",
                  "device_all_array", "scan", "columns_total"):
        if field not in tq[0]:
            print("FAIL: table line missing %r (got %r)"
                  % (field, sorted(tq[0])))
            return 1
    if not tq[0]["parity"]:
        print("FAIL: table query device plan and host row path "
              "disagreed: %r" % tq[0])
        return 1
    if not tq[0]["device_all_array"]:
        print("FAIL: table query device side left the array path: %r"
              % tq[0])
        return 1
    tscan = tq[0]["scan"]
    if not isinstance(tscan, dict) \
            or "columns_read" not in tscan \
            or len(tscan["columns_read"]) >= tq[0]["columns_total"]:
        print("FAIL: table query scan did not prune columns: %r"
              % (tscan,))
        return 1
    # ISSUE 10: the pane-plane stream section — the dstream window
    # line (when the child ran) must carry pane accounting, and
    # benchmarks/stream_rate.py --smoke must emit both the sustained-
    # ingest line (records/s at a fixed p99 batch-latency budget) and
    # the window-scaling A/B with all three series.  Wall ratios are
    # not graded here (CI boxes are too noisy; the acceptance numbers
    # live in BENCH_*.json) — but the schema and the pane-mode
    # indicators are: a refactor that silently drops the pane path
    # reports mode != inv/tree/flat and fails.
    ds = [p for p in parsed
          if str(p.get("metric", "")).startswith("dstream_window")]
    if ds and not isinstance(ds[0].get("panes"), dict):
        print("FAIL: dstream_window line carries no panes dict: %r"
              % sorted(ds[0]))
        return 1
    sproc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "benchmarks", "stream_rate.py"), "--smoke"],
        capture_output=True, text=True, env=env,
        timeout=int(env.get("BENCH_SMOKE_TIMEOUT", "1500")))
    sys.stderr.write(sproc.stderr[-2000:])
    print(sproc.stdout)
    if sproc.returncode != 0:
        print("FAIL: stream_rate.py exited %d" % sproc.returncode)
        return 1
    sparsed = []
    for ln in sproc.stdout.splitlines():
        if ln.startswith("{"):
            try:
                sparsed.append(json.loads(ln))
            except ValueError as e:
                print("FAIL: unparseable stream_rate JSON %r: %s"
                      % (ln[:120], e))
                return 1
    scale = [p for p in sparsed
             if p.get("metric") == "stream_window_scaling"]
    if not scale:
        print("FAIL: no stream_window_scaling line")
        return 1
    for field in ("ratios", "pane_ms", "inv_ms", "old_ms",
                  "pane_growth", "inv_growth", "old_growth"):
        if field not in scale[0]:
            print("FAIL: scaling line missing %r (got %r)"
                  % (field, sorted(scale[0])))
            return 1
    if len(scale[0]["pane_ms"]) != len(scale[0]["ratios"]) \
            or len(scale[0]["inv_ms"]) != len(scale[0]["ratios"]):
        print("FAIL: scaling series/ratio length mismatch: %r"
              % scale[0])
        return 1
    rate = [p for p in sparsed if p.get("metric") == "stream_rate"]
    if not rate:
        print("FAIL: no stream_rate line")
        return 1
    for field in ("value", "p99_batch_ms", "batch_s", "target_p99_ms",
                  "sustained", "rates_tried", "panes"):
        if field not in rate[0]:
            print("FAIL: stream_rate line missing %r (got %r)"
                  % (field, sorted(rate[0])))
            return 1
    if rate[0].get("panes", {}).get("mode") not in ("inv", "tree",
                                                    "flat", "pane"):
        print("FAIL: stream_rate drove a non-pane window (mode=%r)"
              % rate[0].get("panes", {}).get("mode"))
        return 1
    print("OK stream: rate=%.0f records/s (p99 %.0fms <= %.0fms: %s) "
          "scaling pane/inv/old growth=%.2f/%.2f/%.2f"
          % (rate[0]["value"], rate[0]["p99_batch_ms"],
             rate[0]["target_p99_ms"], rate[0]["sustained"],
             scale[0]["pane_growth"], scale[0]["inv_growth"],
             scale[0]["old_growth"]))
    print("OK: %d JSON lines, ooc pipeline+phases fields present "
          "(waves=%d idle=%.3f depth=%d donated=%s narrow=%.0fms "
          "fallbacks=%d groupmap=%.1fx coded=%.2fx adapt cold/warm "
          "ladder=%d/%d hits=%d/%d service warm=%.1fx compiles=%d/%d "
          "conc=%.2fx bulk=%.1fx table=%.1fx cols=%d/%d "
          "reuse=%.0fx/%.0fx recovery=%.2fx resumed=%d)"
          % (len(parsed), pipe["waves"], pipe["device_idle_frac"],
             pipe["pipeline_depth"], pipe["donated"],
             phases["narrow_ms"], len(ooc[0]["fallback_reasons"]),
             gm[0]["value"], coded[0]["value"],
             cold["ladder_retries"], warm["ladder_retries"],
             cold["store_hits"], warm["store_hits"],
             sv[0]["value"], sv[0]["cold"]["compiles"],
             sv[0]["warm"]["compiles"],
             conc.get("ratio_vs_slower_solo", 0.0),
             bk[0]["value"], tq[0]["value"],
             len(tscan["columns_read"]), tq[0]["columns_total"],
             ruse["speedup"], part["speedup"],
             jr[0]["value"], jr[0]["resumed_stages"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
