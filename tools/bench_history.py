#!/usr/bin/env python
"""bench_history: append a bench run's headline ratios to the bench
trajectory and diff them against the previous entry (ISSUE 15
satellite).

Every bench/smoke run prints JSON metric lines; until now they died
with the CI log — the trajectory file was empty and a slow regression
across PRs was invisible.  This tool extracts the headline RATIOS
(dimensionless, so they are comparable across machines in a way raw
walls are not), appends one JSON line per run to
``BENCH_TRAJECTORY.jsonl``, and prints the deltas vs the previous
entry::

    python tools/bench_smoke_check.py | tee /tmp/bench.out
    python tools/bench_history.py /tmp/bench.out --label ci

Tracked ratios (whatever the run emitted):

    reduce_vs_baseline        device reduceByKey vs host process
    groupmap_device_vs_host   SegMapOp A/B
    table_device_vs_host      columnar query plane A/B
    bulk_channel_vs_bridge    bulk data plane vs pickled bridge
    coded_overhead            rs(4,2) no-fault overhead (<= 1.15)
    adapt_warm_vs_cold        warm wall / cold wall (< 1)
    service_warm_submit       cold/warm first-wave latency (>= 3)
    result_reuse              repeated-query cold/warm wall (>= 5)
    health_plane_overhead     sink on/off wall ratio (<= 1.03)
    ledger_plane_overhead     ledger on/off wall ratio (<= 1.03)
    lockcheck_overhead        sanitizer on/off wall ratio (<= 1.03)
    journal_recovery          journal on/off wall ratio (<= 1.02)

The trajectory is plain JSON lines (one entry per run) so ``git
diff`` reads it; corrupt lines skip at load.  The diff is
informational by default; ``--gate PCT`` exits 1 when any tracked
ratio regressed by more than PCT percent vs the previous entry
(higher-is-better metrics dropping, overhead metrics rising).
"""

import argparse
import json
import os
import sys
import time

# metric-line name -> (trajectory key, higher_is_better)
HEADLINES = {
    "reduceByKey_GBps_per_chip": ("reduce_vs_baseline", True),
    "reduceByKey_GBps_per_chip_EMULATED_CPU":
        ("reduce_vs_baseline", True),
    "group_mapvalues_device_vs_host": ("groupmap_device_vs_host",
                                       True),
    "table_query_device_vs_host": ("table_device_vs_host", True),
    "bulk_channel_vs_bridge": ("bulk_channel_vs_bridge", True),
    "coded_shuffle_overhead": ("coded_overhead", False),
    "adapt_warm_vs_cold": ("adapt_warm_vs_cold", False),
    "adaptive_code": ("adaptive_code", False),
    "skew_replan": ("skew_replan", True),
    "service_warm_submit": ("service_warm_submit", True),
    "aot_restart": ("aot_restart", True),
    "result_reuse": ("result_reuse", True),
    "health_plane_overhead": ("health_plane_overhead", False),
    "ledger_plane_overhead": ("ledger_plane_overhead", False),
    "lockcheck_overhead": ("lockcheck_overhead", False),
    "journal_recovery": ("journal_recovery", False),
}


def extract_ratios(lines):
    """JSON metric lines -> {trajectory key: ratio}.  The reduce line
    contributes its vs_baseline ratio (the GB/s value is
    machine-bound); every other line contributes its `value`."""
    out = {}
    for ln in lines:
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            rec = json.loads(ln)
        except ValueError:
            continue
        metric = str(rec.get("metric", ""))
        base = metric
        for suffix in ("_EMULATED_CPU",):
            if base.endswith(suffix) and base not in HEADLINES:
                base = base[:-len(suffix)]
        ent = HEADLINES.get(metric) or HEADLINES.get(base)
        if ent is None:
            continue
        key, _ = ent
        if key == "reduce_vs_baseline":
            v = rec.get("vs_baseline")
        else:
            v = rec.get("value")
        if isinstance(v, (int, float)):
            out[key] = round(float(v), 4)
    return out


def load_trajectory(path):
    entries = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    entries.append(json.loads(ln))
                except ValueError:
                    continue            # corrupt line: skip, never fail
    except OSError:
        pass
    return entries


def diff_entries(prev, cur):
    """[(key, prev, cur, pct_change, regressed)] for every ratio both
    entries carry.  pct is signed in the metric's GOOD direction:
    positive = improved."""
    rows = []
    pr = (prev or {}).get("ratios", {})
    cr = cur.get("ratios", {})
    better = {key: hib for _, (key, hib) in HEADLINES.items()}
    for key in sorted(set(pr) & set(cr)):
        a, b = float(pr[key]), float(cr[key])
        if a == 0:
            continue
        pct = (b - a) / abs(a) * 100.0
        if not better.get(key, True):
            pct = -pct                  # lower is better: flip sign
        rows.append((key, a, b, round(pct, 2), pct < 0))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="bench_history", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("bench_out",
                    help="file holding a bench run's stdout "
                         "(JSON metric lines; '-' reads stdin)")
    ap.add_argument("--out", default=None,
                    help="trajectory file (default: "
                         "BENCH_TRAJECTORY.jsonl beside this repo)")
    ap.add_argument("--label", default="",
                    help="free-form tag for the entry (e.g. ci, "
                         "local, r15)")
    ap.add_argument("--gate", type=float, default=None, metavar="PCT",
                    help="exit 1 when any ratio regressed more than "
                         "PCT%% vs the previous entry")
    args = ap.parse_args(argv)

    if args.bench_out == "-":
        lines = sys.stdin.read().splitlines()
    else:
        with open(args.bench_out) as f:
            lines = f.read().splitlines()
    ratios = extract_ratios(lines)
    if not ratios:
        print("FAIL: no headline metric lines found in %r"
              % args.bench_out)
        return 1

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = args.out or os.path.join(repo, "BENCH_TRAJECTORY.jsonl")
    entries = load_trajectory(path)
    prev = entries[-1] if entries else None
    entry = {"seq": (prev.get("seq", 0) + 1) if prev else 1,
             "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
             "label": args.label, "ratios": ratios}
    with open(path, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    print("recorded entry %d (%d ratios) -> %s"
          % (entry["seq"], len(ratios), path))

    if prev is None:
        print("no previous entry to diff against (trajectory was "
              "empty)")
        return 0
    rows = diff_entries(prev, entry)
    regressed = []
    for key, a, b, pct, bad in rows:
        print("  %-26s %8.3f -> %8.3f  (%+.1f%% %s)"
              % (key, a, b, pct, "regressed" if bad else "ok"))
        if bad and args.gate is not None and -pct > args.gate:
            regressed.append((key, pct))
    new_keys = sorted(set(entry["ratios"]) - set(
        (prev.get("ratios") or {})))
    if new_keys:
        print("  new since previous entry: %s" % ", ".join(new_keys))
    if regressed:
        print("FAIL: regressed beyond --gate %.1f%%: %s"
              % (args.gate, ", ".join("%s (%.1f%%)" % r
                                      for r in regressed)))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
